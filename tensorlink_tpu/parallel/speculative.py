"""Speculative decoding for the serving engines (ROADMAP item 2).

Decode is pinned at 0.63-0.68 of the bandwidth roofline (BENCH_r05
``fraction_attained``) because every emitted token pays a full weight
pass. The only way past that bound is emitting MORE THAN ONE token per
weight pass: a cheap draft proposes K tokens ahead, and the target
model verifies all K (+1 bonus) positions in ONE jitted pass — the
``verify-K`` form of the per-row cache machinery in nn/attention.py
(T == K+1 decode-frontier writes with per-query causality, so a
rejected suffix never influenced its accepted prefix and rollback is
just an index reset).

Two drafting strategies share the exact same verify program:

- **draft model** (``SpeculativeDecoder(draft=...)``): a small sibling
  from the model zoo runs K+1 single-token steps over its OWN per-slot
  KV cache (kept in lockstep with the target's frontier — the extra
  step writes the k/v of the last proposal so a fully-accepted round
  leaves no hole in the draft cache);
- **n-gram / prompt-lookup** (``draft=None``): proposals come from the
  request's own context — the most recent recurrence of the trailing
  n-gram, continued. No second model, no draft cache; covers targets
  with no small sibling (Llama-8B) for free. Any proposal is
  correctness-safe — verification fixes it — so a row with no match
  just proposes its pending token (counted as a fallback).

Acceptance math: per-token acceptance rate ``a`` yields an expected
``(1 - a^(K+1)) / (1 - a)`` emitted tokens per target weight pass
(plus the bonus); the serving engines report the realized
``accepted_tokens_per_weight_pass`` per request and in aggregate.

Greedy output is token-identical with speculation on or off; at
``temperature > 0`` the standard rejection-sampling test
(``parallel/inference.py spec_verify``) keeps the output distribution
exactly the target's.
"""

from __future__ import annotations

import time
from dataclasses import dataclass

import jax
import jax.numpy as jnp
import numpy as np

from tensorlink_tpu.parallel.inference import sample_logits

__all__ = [
    "AdaptiveKController",
    "SpecConfig",
    "SpeculativeDecoder",
    "autopair_draft",
    "default_draft_candidates",
    "ngram_propose",
]

# RNG stream salts: speculation draws (draft proposals, accept/reject
# uniforms + residual resampling) must not collide with the engine's
# per-position sampling stream fold_in(key(seed), position)
SALT_DRAFT = 0x5D
SALT_VERIFY = 0x5E

# fixed per-extra-verify-position cost the controller charges on top of
# the draft steps: the verify pass is one weight read whatever K is,
# but each drafted position still pays attention/logits compute and
# _slot_ub block reservations — without this, a free proposer (n-gram)
# would pin K at k_max even at zero acceptance
POSITION_COST = 0.02


@dataclass(frozen=True)
class SpecConfig:
    """``k``: drafted tokens per verify pass (each pass emits 1..k+1
    tokens); under the adaptive controller this is ``k_max``, the
    compiled proposal width. ``rounds``: (draft + verify) rounds per
    dispatched chunk — the spec analogue of ``decode_chunk``; one
    dispatch advances a live row by up to ``rounds * (k + 1)`` tokens.
    ``ngram``: match length for prompt-lookup drafting (draft-model
    mode ignores it).

    Adaptive knobs (all default OFF — a plain SpecConfig behaves
    exactly like the static PR-7 one):

    - ``adaptive``: per-request masked K — each row's effective K is a
      TRACED operand of the one spec-chunk program, chosen online by
      :class:`AdaptiveKController` from that request's measured
      acceptance. No retrace, no second program.
    - ``k_min``: controller floor (>= 1; a verify pass always emits at
      least one token anyway).
    - ``entropy_exit``: draft-model early exit — when the draft's own
      token entropy (nats) spikes past this at some step, the row
      stops proposing there and later positions are treated as never
      proposed (the verifier would reject them; the draft stops paying
      for them). None = off. n-gram mode ignores it (no draft
      distribution to measure).
    - ``self_heal_accept``: acceptance floor below which the ENGINE
      downgrades its speculation mode (draft -> n-gram -> off) at the
      next idle point — the tldiag LOW-ACCEPT flag made self-healing.
      None = advisory only.
    - ``ewma``: smoothing of the controller's acceptance estimate.
    - ``draft_cost``: one draft step's cost relative to a target
      weight pass (the controller's cost model; auto-pairing replaces
      it with the measured value).
    """

    k: int = 4
    rounds: int = 2
    ngram: int = 2
    adaptive: bool = False
    k_min: int = 1
    entropy_exit: float | None = None
    self_heal_accept: float | None = None
    ewma: float = 0.25
    draft_cost: float = 0.5

    def __post_init__(self):
        if self.k < 1:
            raise ValueError(f"spec k must be >= 1, got {self.k}")
        if self.rounds < 1:
            raise ValueError(f"spec rounds must be >= 1, got {self.rounds}")
        if self.ngram < 2:
            raise ValueError(
                f"ngram must be >= 2 (1 would match every token), "
                f"got {self.ngram}"
            )
        if not 1 <= self.k_min <= self.k:
            raise ValueError(
                f"k_min must be in [1, k={self.k}], got {self.k_min}"
            )
        if self.entropy_exit is not None and self.entropy_exit <= 0:
            raise ValueError(
                f"entropy_exit must be > 0 nats, got {self.entropy_exit}"
            )
        if self.self_heal_accept is not None and not (
            0.0 < self.self_heal_accept < 1.0
        ):
            raise ValueError(
                f"self_heal_accept must be in (0, 1), "
                f"got {self.self_heal_accept}"
            )
        if not 0.0 < self.ewma <= 1.0:
            raise ValueError(f"ewma must be in (0, 1], got {self.ewma}")
        if self.draft_cost < 0.0:
            raise ValueError(
                f"draft_cost must be >= 0, got {self.draft_cost}"
            )

    @classmethod
    def auto(cls, k: int = 4, **kw) -> "SpecConfig":
        """The self-tuning preset: adaptive K, draft early exit at 2.5
        nats (well past a confident head, well under uniform for any
        real vocab), and LOW-ACCEPT self-healing at tldiag's own 0.3
        threshold."""
        kw.setdefault("adaptive", True)
        kw.setdefault("entropy_exit", 2.5)
        kw.setdefault("self_heal_accept", 0.3)
        return cls(k=k, **kw)


class AdaptiveKController:
    """Per-request masked-K controller: turns the measured acceptance
    already flowing into ``stats()["spec"]`` back into the next
    dispatch's per-row effective K.

    Model: per-token acceptance ``a`` makes a K-proposal round emit
    ``e(a, k) = (1 - a^(k+1)) / (1 - a)`` expected tokens for a cost of
    one target pass plus ``k + 1`` draft steps at ``draft_cost`` each
    (plus POSITION_COST per drafted position). The controller picks the
    ``k`` in ``[k_min, k_max]`` maximizing expected tokens per cost,
    per request, from an EWMA of that request's own acceptance (new
    requests start from the cross-request prior, which the autotune
    store can seed across restarts — runtime/autotune.py)."""

    def __init__(self, cfg: SpecConfig, *, draft_cost: float | None = None,
                 prior: dict | None = None):
        self.cfg = cfg
        self.draft_cost = (
            float(draft_cost) if draft_cost is not None else cfg.draft_cost
        )
        self._acc: dict[int, float] = {}  # rid -> acceptance EWMA
        # cross-request prior: what a fresh request starts from
        self.prior_acceptance = 0.6
        if prior:
            a = prior.get("acceptance")
            if isinstance(a, (int, float)) and 0.0 <= a <= 1.0:
                self.prior_acceptance = float(a)
            c = prior.get("draft_cost")
            if draft_cost is None and isinstance(c, (int, float)) and c >= 0:
                self.draft_cost = float(c)
        self._k_cache: dict[int, int] = {}  # milli-acceptance -> k
        self.k_dispatched = 0  # sum of k over dispatched (row, round)s
        self.rounds_dispatched = 0

    # ------------------------------------------------------------ law
    def k_for_acceptance(self, a: float) -> int:
        key = int(round(min(max(a, 0.0), 0.999) * 1000))
        k = self._k_cache.get(key)
        if k is None:
            k = self._argmax_k(key / 1000.0)
            self._k_cache[key] = k
        return k

    def _argmax_k(self, a: float) -> int:
        best_k, best = self.cfg.k_min, -1.0
        for k in range(self.cfg.k_min, self.cfg.k + 1):
            if a >= 0.999:
                e = float(k + 1)
            else:
                e = (1.0 - a ** (k + 1)) / (1.0 - a)
            cost = 1.0 + self.draft_cost * (k + 1) + POSITION_COST * k
            v = e / cost
            if v > best + 1e-9:  # ties go to the smaller k
                best_k, best = k, v
        return best_k

    # ------------------------------------------------------- feedback
    def k_for(self, rid: int) -> int:
        return self.k_for_acceptance(self._acc.get(rid, self.prior_acceptance))

    def observe(self, rid: int, proposed: int, accepted: int) -> None:
        """One drained verify round's truth for one request. ``proposed``
        may be < k (early exit) or 0 (fully exited round — no signal)."""
        if proposed <= 0:
            return
        lam = self.cfg.ewma
        a = accepted / proposed
        cur = self._acc.get(rid, self.prior_acceptance)
        self._acc[rid] = (1.0 - lam) * cur + lam * a

    def forget(self, rid: int) -> None:
        """Fold a finished request's estimate into the prior and drop
        its per-request state."""
        a = self._acc.pop(rid, None)
        if a is not None:
            lam = self.cfg.ewma
            self.prior_acceptance = (
                (1.0 - lam) * self.prior_acceptance + lam * a
            )

    def note_dispatch(self, ks) -> None:
        for k in ks:
            self.k_dispatched += int(k)
            self.rounds_dispatched += 1

    # ---------------------------------------------------------- stats
    def k_mean(self) -> float:
        if not self.rounds_dispatched:
            return float(self.k_for_acceptance(self.prior_acceptance))
        return self.k_dispatched / self.rounds_dispatched

    def prior(self) -> dict:
        """The persistable posterior (runtime/autotune.py ``k_prior``):
        what a restarted engine should start its controller from."""
        return {
            "k": self.k_for_acceptance(self.prior_acceptance),
            "acceptance": round(self.prior_acceptance, 4),
            "draft_cost": round(self.draft_cost, 4),
        }


def ngram_propose(ids, valid, index, tok, k: int, n: int):
    """Prompt-lookup drafting, fully on device: for each row find the
    most recent slot where the trailing n-gram (the last ``n-1``
    committed tokens followed by the pending token ``tok``) already
    occurred, and propose the ``k`` tokens that followed it.

    ``ids`` [S, L] slot-aligned token ids (pads hold garbage — excluded
    via ``valid``); ``valid`` [S, L] real-token slots; ``index`` [S]
    write frontier (the pending token's slot); ``tok`` [S].

    Returns ``(proposals [S, k] int32, found [S] bool)``. A row with no
    match proposes its pending token repeated — verification makes any
    proposal safe, it just wastes the pass (callers count it as a
    fallback)."""
    S, L = ids.shape
    pos = jnp.arange(L)
    # match window [p, p+n-1] must sit entirely in committed history:
    # valid[p] plus an end bound suffices (the valid region of a row is
    # one contiguous [pad_end, index) span)
    ok = valid & ((pos + n - 1)[None, :] < index[:, None])
    ok = ok & (index[:, None] >= n)  # enough history for a gram at all
    # the trailing gram itself must be COMMITTED tokens: contiguous
    # serving rows are left-padded (index counts pads + real tokens),
    # so a short history would otherwise read pad garbage as the gram
    # and hunt for a sequence that never occurred (wasting the pass
    # without even counting as a fallback)
    hist_ok = jnp.ones((ids.shape[0],), bool)
    for j in range(n - 1):
        slot_j = jnp.clip(index[:, None] - (n - 1) + j, 0, L - 1)
        gram_j = jnp.take_along_axis(ids, slot_j, axis=1)  # [S, 1]
        hist_ok = hist_ok & jnp.take_along_axis(valid, slot_j, axis=1)[:, 0]
        ok = ok & (ids[:, jnp.minimum(pos + j, L - 1)] == gram_j)
    ok = ok & hist_ok[:, None]
    ok = ok & (ids[:, jnp.minimum(pos + n - 1, L - 1)] == tok[:, None])
    best = jnp.max(jnp.where(ok, pos[None, :], -1), axis=1)  # [S]
    found = best >= 0
    p_idx = best[:, None] + n + jnp.arange(k)[None, :]  # [S, k]
    props = jnp.take_along_axis(ids, jnp.clip(p_idx, 0, L - 1), axis=1)
    real = found[:, None] & (p_idx < index[:, None])
    props = jnp.where(real, props, tok[:, None])
    return props.astype(jnp.int32), found


class SpeculativeDecoder:
    """Drafting side of speculative serving, shared by the contiguous
    and paged engines (parallel/serving.py): owns the draft engine (if
    any), the per-slot draft cache layout, and the traced draft-scan /
    n-gram proposal functions the engines splice into their ONE spec
    chunk program. The verify side is the target model itself plus
    ``inference.spec_verify``."""

    def __init__(self, engine, draft, cfg: SpecConfig):
        self.engine = engine
        self.draft = draft
        self.cfg = cfg
        self.mode = "draft" if draft is not None else "ngram"
        if draft is not None:
            if draft.rolling or draft.kv_seq_shard:
                raise NotImplementedError(
                    "draft engines must use the plain monotone cache "
                    "(no rolling_cache / kv_seq_shard)"
                )
            tv = getattr(
                getattr(engine.model, "cfg_obj", None), "vocab_size", None
            )
            dv = getattr(
                getattr(draft.model, "cfg_obj", None), "vocab_size", None
            )
            if tv is not None and dv is not None and tv != dv:
                raise ValueError(
                    f"draft vocab {dv} != target vocab {tv}: drafted "
                    "token ids would be meaningless to the target"
                )

    @property
    def draft_params(self):
        return self.draft.params if self.draft is not None else None

    # ------------------------------------------------------------- state
    def init_draft_caches(self, slots: int, length: int):
        """Per-slot draft KV cache in the serving (vec-index) form:
        same slot layout and capacity as the target's cache view, so
        the two frontiers stay in lockstep and one validity mask
        serves both."""
        caches = self.draft.model.init_caches(
            slots, length, dtype=self.draft.cache_dtype
        )
        return jax.tree.map(
            lambda c: jnp.zeros((slots,), jnp.int32)
            if getattr(c, "ndim", None) == 0
            and jnp.issubdtype(c.dtype, jnp.integer) else c,
            caches,
        )

    # ----------------------------------------------------------- drafting
    def build_draft_fn(self, gen):
        """Traced K+1-step draft scan: feeds ``tok`` then its own
        proposals through the draft model's per-slot cache, returning
        ``(proposals [S, K], draft_logits [S, K, V], new_caches,
        k_live [S])``.

        The scan runs K+1 steps (not K): the last step writes the k/v
        of proposal d_K into the draft cache and discards its own
        proposal, so when the verify pass accepts all K (+ bonus) the
        draft cache has no hole at the new frontier.

        Adaptive masking: ``k_eff`` [S] caps how many proposals each
        row may spend this round, and ``cfg.entropy_exit`` retires a
        row at the first step whose draft distribution's entropy
        spikes past the threshold — later proposals would mostly be
        rejected anyway. ``k_live[s] <= k_eff[s]`` is the number of
        proposals row ``s`` actually stands behind; emission and
        acceptance accounting clamp there (``spec_verify`` k_live).
        Each scan step runs under a ``lax.cond`` on "any row still
        needs this step", so when every row has exited (or every
        row's k_eff is satisfied) the remaining draft weight passes
        are SKIPPED, not just ignored — the early-exit FLOP saving is
        real, not cosmetic. Rows needing fewer steps than the batch
        maximum keep writing harmless proposals past their own
        frontier (overwritten before ever being attended, the same
        rollback contract as rejection)."""
        model = self.draft.model
        K = self.cfg.k
        thresh = self.cfg.entropy_exit
        temperature = float(gen.temperature)
        top_k, top_p = int(gen.top_k), float(gen.top_p)
        # the cond-skip branch must emit logits of a statically known
        # width; a model that doesn't declare its vocab just runs every
        # step (masking still applies — only the FLOP skip is lost)
        V = getattr(getattr(model, "cfg_obj", None), "vocab_size", None)

        def run(dparams, dcaches, tok, n_valid, seed, mask, k_eff, live):
            def real_step(args):
                dcaches, tok, t = args
                positions = (n_valid + t)[:, None]
                logits, dcaches = model.apply(
                    dparams, tok[:, None], caches=dcaches,
                    positions=positions, mask=mask,
                )
                # f32 so both cond branches agree on dtype (the cast is
                # exact; every consumer upcasts before use anyway)
                return logits[:, -1].astype(jnp.float32), dcaches

            def skip_step(args):
                dcaches, tok, _ = args
                # every row is done for this round: emit a flat (and
                # therefore max-entropy) distribution so nothing
                # downstream can mistake it for a real proposal
                return (
                    jnp.zeros((tok.shape[0], V), jnp.float32), dcaches
                )

            def step(carry, t):
                dcaches, tok, alive = carry
                # row s still needs step t while t <= its proposal
                # budget (step t writes the k/v of fed token t — the
                # slot an accepted prefix of k_live proposals ends at)
                # and its entropy has not yet spiked
                need = live & alive & (t <= k_eff)
                if V is None:
                    lg, dcaches = real_step((dcaches, tok, t))
                else:
                    lg, dcaches = jax.lax.cond(
                        jnp.any(need), real_step, skip_step,
                        (dcaches, tok, t),
                    )
                if temperature == 0.0:
                    nxt = jnp.argmax(lg, -1).astype(jnp.int32)
                else:
                    def samp(s, n, row):
                        key = jax.random.fold_in(
                            jax.random.fold_in(jax.random.key(s), n),
                            SALT_DRAFT,
                        )
                        return sample_logits(
                            row, key, temperature, top_k, top_p
                        )

                    nxt = jax.vmap(samp)(
                        seed, n_valid + t + 1, lg
                    ).astype(jnp.int32)
                # a skipped/retired row keeps feeding its old token so
                # the carry stays well-formed; its proposals are masked
                # out of acceptance via k_live either way
                nxt = jnp.where(need, nxt, tok)
                if thresh is not None:
                    p = jax.nn.softmax(lg.astype(jnp.float32), axis=-1)
                    ent = -jnp.sum(
                        p * jnp.log(jnp.maximum(p, 1e-20)), axis=-1
                    )
                    alive = alive & need & (ent <= thresh)
                else:
                    alive = alive & need
                return (dcaches, nxt, alive), (nxt, lg, alive)

            alive0 = jnp.ones_like(live)
            (dcaches, _, _), (props, dlg, alive_t) = jax.lax.scan(
                step, (dcaches, tok, alive0), jnp.arange(K + 1)
            )
            # proposal d_{t+1} (props[t]) is trusted iff the row was
            # still alive AFTER step t: its entropy checks passed at
            # every step up to and including the one that drew it
            k_live = jnp.minimum(
                alive_t[:K].astype(jnp.int32).sum(axis=0), k_eff
            )
            # props[t] = d_{t+1}; keep d_1..d_K and their distributions
            return (
                props[:K].T,               # [S, K]
                dlg[:K].transpose(1, 0, 2),  # [S, K, V]
                dcaches,
                k_live,
            )

        return run

    def verify_key(self, seed, n_valid):
        """Per-row rejection-sampling key: a function of (request seed,
        logical position) only — like the engine's sampling stream, so
        a request's draws are independent of slot assignment and
        co-tenant traffic."""
        return jax.random.fold_in(
            jax.random.fold_in(jax.random.key(seed), n_valid), SALT_VERIFY
        )


# --------------------------------------------------------- draft pairing
def _vocab_of(engine) -> int | None:
    return getattr(getattr(engine.model, "cfg_obj", None), "vocab_size", None)


def default_draft_candidates(engine) -> list[tuple[str, object]]:
    """The model zoo's free draft pair for any target: its own int8
    weight-only sibling — half the weight bytes per draft step on a
    memory-bound decode, and int8 almost always preserves the argmax
    (the bench's ``int8_quality`` KL measures exactly that), so greedy
    acceptance is a real model property. Thunks, not engines: a
    candidate that never gets measured never allocates."""
    from tensorlink_tpu.parallel.inference import InferenceEngine

    def int8_sibling():
        return InferenceEngine(
            engine.mesh, engine.model, engine.params,
            max_len=engine.max_len, cache_dtype=engine.cache_dtype,
            data_axis=engine.data_axis, model_axis=engine.model_axis,
            quantize="int8",
        )

    return [("int8-sibling", int8_sibling)]


def autopair_draft(
    engine,
    gen,
    *,
    candidates: list[tuple[str, object]] | None = None,
    cfg: SpecConfig | None = None,
    prompts=None,
    max_new: int = 16,
    slots: int = 2,
    recorder=None,
) -> dict:
    """Measured draft pairing (ROADMAP item 3): a short calibration
    burst at engine start decides HOW this engine should speculate —
    not tokens-per-weight heuristics, wall-clock on this chip.

    Runs the burst prompts through (a) a non-speculative scheduler —
    the baseline any speculation must beat, (b) each vocab-compatible
    candidate draft, LARGEST first (bigger sibling = higher acceptance;
    first one whose measured accepted-tokens-per-second beats the
    baseline wins), and (c) n-gram self-speculation as the free
    fallback. Verdict order: best paying draft > paying n-gram >
    non-spec.

    Returns ``{"mode": "draft"|"ngram"|"nonspec", "name", "draft":
    engine-or-None, "spec": SpecConfig-or-None, "measured": {name:
    tokens_per_sec}, "baseline_tokens_per_sec", "calibration_s",
    "persistable": {...}}`` — splat ``draft=`` / ``speculative=`` from
    it into a serving-engine ctor. ``persistable`` is the JSON-safe
    summary (no live engines) to hand ``save_autotune(draft_pair=...)``
    so a restart skips the burst entirely.

    Candidates are built LAZILY, one at a time, in the order given
    (list them largest-first — bigger sibling = higher acceptance) and
    each loser is released before the next builds, so a zoo of drafts
    never holds more than one candidate's weights at once."""
    from tensorlink_tpu.parallel.serving import ContinuousBatchingEngine

    t_start = time.perf_counter()
    cfg = cfg or SpecConfig()
    if prompts is None:
        vocab = _vocab_of(engine) or 256
        r = np.random.default_rng(0)
        prompts = [r.integers(0, vocab, (n,)) for n in (8, 13, 6, 10)]
    if candidates is None:
        candidates = default_draft_candidates(engine)

    def burst(draft_eng, spec_cfg) -> float:
        sch = ContinuousBatchingEngine(
            engine, slots=slots, gen=gen, decode_chunk=max(cfg.k, 4),
            prefill_block=16, draft=draft_eng, speculative=spec_cfg,
            recorder=recorder,
        )
        sch.result(sch.submit(prompts[0], max_new=max_new))  # compile
        t0 = time.perf_counter()
        rids = [sch.submit(p, max_new=max_new) for p in prompts]
        sch.run_until_idle()
        dt = time.perf_counter() - t0
        ntok = sum(len(sch.result(rid)) for rid in rids)
        return ntok / dt if dt > 0 else 0.0

    measured: dict[str, float] = {}
    base_tps = burst(None, None)
    measured["nonspec"] = round(base_tps, 1)
    tvocab = _vocab_of(engine)

    def _record(kind: str, **data) -> None:
        if recorder is not None:
            try:
                recorder.record(kind, **data)
            except Exception:  # noqa: BLE001 — telemetry only
                pass

    choice = {"mode": "nonspec", "name": "nonspec", "draft": None,
              "spec": None}
    for name, cand in candidates:
        # build INSIDE the loop and release losers before the next
        # candidate builds: a zoo next to a large target must never
        # hold every draft's weights at once
        d = cand() if callable(cand) else cand
        dvocab = _vocab_of(d)
        if tvocab is not None and dvocab is not None and dvocab != tvocab:
            _record(
                "spec.autopair_skip", name=name,
                reason=f"vocab {dvocab} != target {tvocab}",
            )
            del d
            continue
        try:
            tps = burst(d, cfg)
        except (ValueError, NotImplementedError) as e:
            _record("spec.autopair_skip", name=name, reason=str(e)[:200])
            del d
            continue
        measured[name] = round(tps, 1)
        if tps > base_tps:
            choice = {"mode": "draft", "name": name, "draft": d,
                      "spec": cfg}
            break
        del d
    if choice["mode"] == "nonspec":
        ng_tps = burst(None, cfg)
        measured["ngram"] = round(ng_tps, 1)
        if ng_tps > base_tps:
            choice = {"mode": "ngram", "name": "ngram", "draft": None,
                      "spec": cfg}
    choice["measured"] = measured
    choice["baseline_tokens_per_sec"] = round(base_tps, 1)
    choice["calibration_s"] = round(time.perf_counter() - t_start, 3)
    # the JSON-safe form for the autotune store: everything about the
    # verdict EXCEPT the live engine and config objects
    choice["persistable"] = {
        "mode": choice["mode"], "name": choice["name"],
        "measured": measured,
        "baseline_tokens_per_sec": choice["baseline_tokens_per_sec"],
        "calibration_s": choice["calibration_s"],
    }
    _record(
        "spec.autopair", mode=choice["mode"], name=choice["name"],
        measured=measured,
    )
    return choice
