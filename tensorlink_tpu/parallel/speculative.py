"""Speculative decoding for the serving engines (ROADMAP item 2).

Decode is pinned at 0.63-0.68 of the bandwidth roofline (BENCH_r05
``fraction_attained``) because every emitted token pays a full weight
pass. The only way past that bound is emitting MORE THAN ONE token per
weight pass: a cheap draft proposes K tokens ahead, and the target
model verifies all K (+1 bonus) positions in ONE jitted pass — the
``verify-K`` form of the per-row cache machinery in nn/attention.py
(T == K+1 decode-frontier writes with per-query causality, so a
rejected suffix never influenced its accepted prefix and rollback is
just an index reset).

Two drafting strategies share the exact same verify program:

- **draft model** (``SpeculativeDecoder(draft=...)``): a small sibling
  from the model zoo runs K+1 single-token steps over its OWN per-slot
  KV cache (kept in lockstep with the target's frontier — the extra
  step writes the k/v of the last proposal so a fully-accepted round
  leaves no hole in the draft cache);
- **n-gram / prompt-lookup** (``draft=None``): proposals come from the
  request's own context — the most recent recurrence of the trailing
  n-gram, continued. No second model, no draft cache; covers targets
  with no small sibling (Llama-8B) for free. Any proposal is
  correctness-safe — verification fixes it — so a row with no match
  just proposes its pending token (counted as a fallback).

Acceptance math: per-token acceptance rate ``a`` yields an expected
``(1 - a^(K+1)) / (1 - a)`` emitted tokens per target weight pass
(plus the bonus); the serving engines report the realized
``accepted_tokens_per_weight_pass`` per request and in aggregate.

Greedy output is token-identical with speculation on or off; at
``temperature > 0`` the standard rejection-sampling test
(``parallel/inference.py spec_verify``) keeps the output distribution
exactly the target's.
"""

from __future__ import annotations

from dataclasses import dataclass

import jax
import jax.numpy as jnp

from tensorlink_tpu.parallel.inference import sample_logits

__all__ = ["SpecConfig", "SpeculativeDecoder", "ngram_propose"]

# RNG stream salts: speculation draws (draft proposals, accept/reject
# uniforms + residual resampling) must not collide with the engine's
# per-position sampling stream fold_in(key(seed), position)
SALT_DRAFT = 0x5D
SALT_VERIFY = 0x5E


@dataclass(frozen=True)
class SpecConfig:
    """``k``: drafted tokens per verify pass (each pass emits 1..k+1
    tokens). ``rounds``: (draft + verify) rounds per dispatched chunk —
    the spec analogue of ``decode_chunk``; one dispatch advances a live
    row by up to ``rounds * (k + 1)`` tokens. ``ngram``: match length
    for prompt-lookup drafting (draft-model mode ignores it)."""

    k: int = 4
    rounds: int = 2
    ngram: int = 2

    def __post_init__(self):
        if self.k < 1:
            raise ValueError(f"spec k must be >= 1, got {self.k}")
        if self.rounds < 1:
            raise ValueError(f"spec rounds must be >= 1, got {self.rounds}")
        if self.ngram < 2:
            raise ValueError(
                f"ngram must be >= 2 (1 would match every token), "
                f"got {self.ngram}"
            )


def ngram_propose(ids, valid, index, tok, k: int, n: int):
    """Prompt-lookup drafting, fully on device: for each row find the
    most recent slot where the trailing n-gram (the last ``n-1``
    committed tokens followed by the pending token ``tok``) already
    occurred, and propose the ``k`` tokens that followed it.

    ``ids`` [S, L] slot-aligned token ids (pads hold garbage — excluded
    via ``valid``); ``valid`` [S, L] real-token slots; ``index`` [S]
    write frontier (the pending token's slot); ``tok`` [S].

    Returns ``(proposals [S, k] int32, found [S] bool)``. A row with no
    match proposes its pending token repeated — verification makes any
    proposal safe, it just wastes the pass (callers count it as a
    fallback)."""
    S, L = ids.shape
    pos = jnp.arange(L)
    # match window [p, p+n-1] must sit entirely in committed history:
    # valid[p] plus an end bound suffices (the valid region of a row is
    # one contiguous [pad_end, index) span)
    ok = valid & ((pos + n - 1)[None, :] < index[:, None])
    ok = ok & (index[:, None] >= n)  # enough history for a gram at all
    # the trailing gram itself must be COMMITTED tokens: contiguous
    # serving rows are left-padded (index counts pads + real tokens),
    # so a short history would otherwise read pad garbage as the gram
    # and hunt for a sequence that never occurred (wasting the pass
    # without even counting as a fallback)
    hist_ok = jnp.ones((ids.shape[0],), bool)
    for j in range(n - 1):
        slot_j = jnp.clip(index[:, None] - (n - 1) + j, 0, L - 1)
        gram_j = jnp.take_along_axis(ids, slot_j, axis=1)  # [S, 1]
        hist_ok = hist_ok & jnp.take_along_axis(valid, slot_j, axis=1)[:, 0]
        ok = ok & (ids[:, jnp.minimum(pos + j, L - 1)] == gram_j)
    ok = ok & hist_ok[:, None]
    ok = ok & (ids[:, jnp.minimum(pos + n - 1, L - 1)] == tok[:, None])
    best = jnp.max(jnp.where(ok, pos[None, :], -1), axis=1)  # [S]
    found = best >= 0
    p_idx = best[:, None] + n + jnp.arange(k)[None, :]  # [S, k]
    props = jnp.take_along_axis(ids, jnp.clip(p_idx, 0, L - 1), axis=1)
    real = found[:, None] & (p_idx < index[:, None])
    props = jnp.where(real, props, tok[:, None])
    return props.astype(jnp.int32), found


class SpeculativeDecoder:
    """Drafting side of speculative serving, shared by the contiguous
    and paged engines (parallel/serving.py): owns the draft engine (if
    any), the per-slot draft cache layout, and the traced draft-scan /
    n-gram proposal functions the engines splice into their ONE spec
    chunk program. The verify side is the target model itself plus
    ``inference.spec_verify``."""

    def __init__(self, engine, draft, cfg: SpecConfig):
        self.engine = engine
        self.draft = draft
        self.cfg = cfg
        self.mode = "draft" if draft is not None else "ngram"
        if draft is not None:
            if draft.rolling or draft.kv_seq_shard:
                raise NotImplementedError(
                    "draft engines must use the plain monotone cache "
                    "(no rolling_cache / kv_seq_shard)"
                )
            tv = getattr(
                getattr(engine.model, "cfg_obj", None), "vocab_size", None
            )
            dv = getattr(
                getattr(draft.model, "cfg_obj", None), "vocab_size", None
            )
            if tv is not None and dv is not None and tv != dv:
                raise ValueError(
                    f"draft vocab {dv} != target vocab {tv}: drafted "
                    "token ids would be meaningless to the target"
                )

    @property
    def draft_params(self):
        return self.draft.params if self.draft is not None else None

    # ------------------------------------------------------------- state
    def init_draft_caches(self, slots: int, length: int):
        """Per-slot draft KV cache in the serving (vec-index) form:
        same slot layout and capacity as the target's cache view, so
        the two frontiers stay in lockstep and one validity mask
        serves both."""
        caches = self.draft.model.init_caches(
            slots, length, dtype=self.draft.cache_dtype
        )
        return jax.tree.map(
            lambda c: jnp.zeros((slots,), jnp.int32)
            if getattr(c, "ndim", None) == 0
            and jnp.issubdtype(c.dtype, jnp.integer) else c,
            caches,
        )

    # ----------------------------------------------------------- drafting
    def build_draft_fn(self, gen):
        """Traced K+1-step draft scan: feeds ``tok`` then its own
        proposals through the draft model's per-slot cache, returning
        ``(proposals [S, K], draft_logits [S, K, V], new_caches)``.

        The scan runs K+1 steps (not K): the last step writes the k/v
        of proposal d_K into the draft cache and discards its own
        proposal, so when the verify pass accepts all K (+ bonus) the
        draft cache has no hole at the new frontier."""
        model = self.draft.model
        K = self.cfg.k
        temperature = float(gen.temperature)
        top_k, top_p = int(gen.top_k), float(gen.top_p)

        def run(dparams, dcaches, tok, n_valid, seed, mask):
            def step(carry, t):
                dcaches, tok = carry
                positions = (n_valid + t)[:, None]
                logits, dcaches = model.apply(
                    dparams, tok[:, None], caches=dcaches,
                    positions=positions, mask=mask,
                )
                lg = logits[:, -1]
                if temperature == 0.0:
                    nxt = jnp.argmax(lg, -1).astype(jnp.int32)
                else:
                    def samp(s, n, row):
                        key = jax.random.fold_in(
                            jax.random.fold_in(jax.random.key(s), n),
                            SALT_DRAFT,
                        )
                        return sample_logits(
                            row, key, temperature, top_k, top_p
                        )

                    nxt = jax.vmap(samp)(
                        seed, n_valid + t + 1, lg
                    ).astype(jnp.int32)
                return (dcaches, nxt), (nxt, lg)

            (dcaches, _), (props, dlg) = jax.lax.scan(
                step, (dcaches, tok), jnp.arange(K + 1)
            )
            # props[t] = d_{t+1}; keep d_1..d_K and their distributions
            return (
                props[:K].T,               # [S, K]
                dlg[:K].transpose(1, 0, 2),  # [S, K, V]
                dcaches,
            )

        return run

    def verify_key(self, seed, n_valid):
        """Per-row rejection-sampling key: a function of (request seed,
        logical position) only — like the engine's sampling stream, so
        a request's draws are independent of slot assignment and
        co-tenant traffic."""
        return jax.random.fold_in(
            jax.random.fold_in(jax.random.key(seed), n_valid), SALT_VERIFY
        )
