"""ShardedTrainer: one jit-compiled train step over the whole mesh.

This is the bridge between a job's stage placement and the data plane —
the TPU answer to DistributedModel's thread-and-socket forward/backward
(src/ml/distributed.py:79-197). A model is split into
(embed, N homogeneous blocks, head); blocks are stacked on a [S, L/S, ...]
leading axis and sharded over ``pipe``; embed/head params live on the mesh
replicated (or TP-sharded by their own specs); the whole
fwd+loss+bwd+optimizer step is ONE XLA program:

- micro-batches stream through the Pipeline's ppermute schedule,
- the ``data`` axis shards the micro-batch dimension (DP),
- the ``model`` axis shards weight matrices by each layer's PartitionSpec
  (TP) inside every stage,
- gradient allreduce over ``data`` and TP collectives over ``model`` are
  inserted by the SPMD partitioner.

So the reference's entire L3+L4 hot path (FORWARD/BACKWARD messages,
per-micro threads, busy-waits) compiles down to ICI collectives inside a
single program launch per step.
"""

from __future__ import annotations

import contextlib
import dataclasses
from typing import Any, Callable

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from tensorlink_tpu.config import TrainConfig
from tensorlink_tpu.nn.module import Module
from tensorlink_tpu.parallel.pp import Pipeline, stack_stage_params
from tensorlink_tpu.parallel.pp1f1b import Pipeline1F1B
from tensorlink_tpu.runtime.metrics import pipeline_bubble_fraction
from tensorlink_tpu.train.optim import (
    apply_updates,
    clip_by_global_norm,
    make_optimizer,
    make_schedule,
)
from tensorlink_tpu.train.trainer import TrainState


@dataclasses.dataclass
class PipelineParts:
    """Model split for the engine. ``head_fn(params, x, batch)`` returns
    the final output (sees ALL params so weight tying works)."""

    embed_fn: Callable[[Any, Any], jax.Array]  # (params, batch) -> [B, ...]
    block: Module  # homogeneous block (for specs)
    block_params: dict  # {"0": ..., "L-1": ...}
    block_fn: Callable[[Any, jax.Array], jax.Array]
    head_fn: Callable[[Any, jax.Array, Any], jax.Array]
    embed_params: Any
    head_params: Any
    # blocks with an auxiliary loss (MoE router load balancing):
    # block_fn_aux(lp, x[, rng]) -> (x, aux). Used when
    # TrainConfig.moe_aux_weight > 0; both pipeline schedules carry it.
    block_fn_aux: Callable[..., Any] | None = None
    # per-batch auxiliary inputs for the blocks (e.g. the attention
    # padding mask): extras_fn(batch) -> pytree with leading [B, ...]
    # leaves, or None. The engine reslices it per micro and hands it to
    # every stage REPLICATED (under seq sharding the mask stays global,
    # which is what lets ring/ulysses apply padding); block_fn /
    # block_fn_aux must then accept a fourth argument.
    extras_fn: Callable[[Any], Any] | None = None
    # whether head_fn + loss reduce UNIFORMLY over token positions
    # (e.g. causal-LM mean CE). Required True for 1F1B at mesh seq>1,
    # where head_loss runs per token shard and results are pmean'd — a
    # position-selective head (BERT's CLS pooling) would silently pool
    # the wrong token on shards > 0. None = unknown = rejected there.
    head_per_token: bool | None = None


def _stacked_spec(
    block: Module, num_stages: int, model_axis="model",
    example_layer_params=None, fsdp_data_size: int = 1,
):
    """Per-block PartitionSpec tree -> stacked [pipe, layer, ...] specs.
    ``example_layer_params`` (one layer's params) lets the spec tree
    follow param-tree surgery the module can't know about (LoRA
    adapters). ``fsdp_data_size`` > 1 additionally shards each block
    leaf over ``data`` (parallel/dp.py fsdp_spec) BEFORE the [pipe,
    layer] prefix is added, so the FSDP dim is always a real weight dim
    and never the stage/layer stacking axes."""
    spec = block.param_spec(model_axis)
    if example_layer_params is not None:
        from tensorlink_tpu.nn.lora import lora_spec_tree

        spec = lora_spec_tree(spec, example_layer_params)
    if fsdp_data_size > 1:
        from tensorlink_tpu.parallel.dp import fsdp_spec_tree

        spec = fsdp_spec_tree(spec, example_layer_params, fsdp_data_size)
    return jax.tree.map(
        lambda s: P("pipe", None, *s),
        spec,
        is_leaf=lambda x: isinstance(x, P),
    )


def reshape_stages(tree, new_stages: int):
    """Re-factor stacked stage leaves [S, Lps, ...] for a different
    pipeline depth: stack_stage_params lays layers out stage-major and
    contiguous (stage s holds layers [s*Lps, (s+1)*Lps)), so changing S
    is a pure reshape through the flat [L, ...] layout — no data
    movement beyond resharding. Works identically on param and
    optimizer-moment trees (same stacked structure)."""

    def leaf(a):
        L = a.shape[0] * a.shape[1]
        if L % new_stages:
            raise ValueError(
                f"{L} layers not divisible by {new_stages} stages"
            )
        return a.reshape(new_stages, L // new_stages, *a.shape[2:])

    return jax.tree.map(leaf, tree)


class ShardedTrainer:
    """Builds the fully sharded train/eval steps for one mesh + model."""

    def __init__(
        self,
        mesh: Mesh,
        cfg: TrainConfig,
        parts: PipelineParts,
        loss_fn: Callable[[jax.Array, Any], jax.Array],
        embed_module: Module | None = None,
        head_module: Module | None = None,
        loss_reduction: str = "uniform_mean",
        tracer=None,
        metrics=None,
        flight=None,
    ):
        """``loss_reduction`` declares how loss_fn reduces over the batch:

        - "uniform_mean": a plain unweighted mean over examples (and, for
          per-token losses, tokens) — every schedule supported.
        - "batch_normalized": normalized by a per-BATCH quantity (e.g.
          mean over the batch's non-pad tokens). GPipe applies loss_fn
          once over the full batch, so this is fine there; 1F1B averages
          per-micro losses, which would SILENTLY differ (pp1f1b.py class
          docstring) — so 1F1B rejects it up front instead.
        """
        self.mesh = mesh
        self.cfg = cfg
        self.parts = parts
        self.loss_fn = loss_fn
        # observability (optional): engine.compile_step / engine.step
        # spans + step_s series + step_seconds histogram per train_step
        # dispatch. Per-stage timing inside the single XLA program is the
        # profiler's job (runtime/profiling.op_breakdown); the schedule-
        # level skew lives in measure_bubble and — on the socket path —
        # in the master's stage{i}_fwd_s series (tracing.straggler_report).
        self.tracer = tracer
        self.metrics = metrics
        self._telemetry = None
        if tracer is not None or metrics is not None:
            from tensorlink_tpu.runtime.tracing import StepTelemetry

            self._telemetry = StepTelemetry(
                tracer, metrics, "engine",
                # num_stages is derived further down — read the mesh here
                {"stages": mesh.shape["pipe"], "micros": cfg.micro_batches},
            )
        # same contract as train/trainer.py: telemetry-enabled trainers
        # account non-finite steps (counter + flight event); the in-jit
        # flag is in stats either way
        if flight is None and (tracer is not None or metrics is not None):
            from tensorlink_tpu.runtime.flight import default_recorder

            flight = default_recorder()
        self.flight = flight
        if loss_reduction not in ("uniform_mean", "batch_normalized"):
            raise ValueError(
                f"unknown loss_reduction {loss_reduction!r}; declare "
                "'uniform_mean' or 'batch_normalized'"
            )
        if loss_reduction == "batch_normalized" and cfg.pp_schedule == "1f1b":
            raise ValueError(
                "pp_schedule='1f1b' computes the batch loss as the "
                "unweighted mean of per-micro losses, which differs from "
                "a per-batch-normalized loss (e.g. mean over the batch's "
                "non-pad tokens). Use pp_schedule='gpipe' (loss_fn runs "
                "once over the full batch there) or renormalize per "
                "example and declare loss_reduction='uniform_mean'."
            )
        self.loss_reduction = loss_reduction  # train_only validated by
        # TrainConfig.__post_init__ (shared with the single-host Trainer)
        self.num_stages = mesh.shape["pipe"]
        L = len(parts.block_params)
        if L % self.num_stages:
            raise ValueError(f"{L} blocks not divisible by pipe={self.num_stages}")
        self.layers_per_stage = L // self.num_stages
        if cfg.pp_schedule not in ("gpipe", "1f1b"):
            raise ValueError(f"unknown pp_schedule {cfg.pp_schedule!r}")
        block_fn = parts.block_fn
        block_fn_aux = parts.block_fn_aux
        self.aux_weight = float(getattr(cfg, "moe_aux_weight", 0.0) or 0.0)
        if self.aux_weight:
            if block_fn_aux is None:
                raise ValueError(
                    "moe_aux_weight > 0 requires PipelineParts.block_fn_aux"
                )
        elif block_fn_aux is not None:
            import logging

            logging.getLogger("tensorlink_tpu.engine").warning(
                "model carries an MoE aux loss but moe_aux_weight=0: the "
                "router trains unregularized"
            )
        # 1F1B recomputes each stage forward inside its per-micro vjp, so
        # it is remat-by-construction; checkpoint only helps GPipe
        if cfg.remat and cfg.pp_schedule == "gpipe":
            block_fn = jax.checkpoint(block_fn)
            if block_fn_aux is not None:
                block_fn_aux = jax.checkpoint(block_fn_aux)
        self.block_fn = block_fn
        self.block_fn_aux = block_fn_aux
        self.seq = mesh.shape.get("seq", 1)
        seq_impl = getattr(parts.block, "attn_impl", None)
        ring = seq_impl in ("ring", "ulysses")  # both need the seq axis bound
        if self.seq > 1:
            if not ring:
                raise ValueError(
                    "mesh seq>1 shards the token dim inside the pipeline; "
                    "build the model with attn_impl='ring' or 'ulysses' "
                    "so attention spans the full sequence over the seq axis"
                )
            if cfg.pp_schedule == "1f1b" and parts.head_per_token is not True:
                # under seq sharding 1F1B runs head_loss per token shard
                # and pmeans — a position-selective head (CLS pooling)
                # silently pools the wrong token on shards > 0
                raise NotImplementedError(
                    "pp_schedule='1f1b' with mesh seq>1 requires "
                    "PipelineParts.head_per_token=True (a head+loss that "
                    "reduces uniformly over token positions, e.g. "
                    "causal-LM mean CE); this model's parts declare "
                    f"head_per_token={parts.head_per_token!r}. Use "
                    "pp_schedule='gpipe', whose head runs on the "
                    "re-assembled full sequence."
                )
        # ring models bind the seq axis even at seq=1 so axis_index /
        # axis_size inside ring_attention_local are always in scope
        self._seq_axis = "seq" if ring else None
        self.pipeline = Pipeline(
            mesh,
            block_fn,
            self.num_stages,
            self.layers_per_stage,
            seq_axis=self._seq_axis,
            block_fn_aux=block_fn_aux,
        )
        sched = make_schedule(
            cfg.schedule, cfg.learning_rate, cfg.warmup_steps, cfg.total_steps
        )
        self.optimizer = make_optimizer(
            cfg.optimizer, sched, cfg.weight_decay,
            moment_dtype=cfg.opt_moment_dtype,
        )
        self.compute_dtype = jnp.dtype(cfg.dtype)

        # shardings ----------------------------------------------------
        from tensorlink_tpu.nn.lora import lora_spec_tree
        from tensorlink_tpu.parallel.dp import fsdp_spec_tree

        fsdp_n = mesh.shape.get("data", 1) if cfg.fsdp else 1
        if cfg.fsdp and fsdp_n <= 1:
            import logging

            logging.getLogger("tensorlink_tpu.engine").warning(
                "fsdp=True on a mesh with data axis size %d: nothing to "
                "shard over — params/moments stay as replicated-DP would "
                "leave them (a mesh-shape sweep hitting data=1 is legal, "
                "so this warns instead of raising)",
                fsdp_n,
            )
        stacked_specs = _stacked_spec(
            parts.block, self.num_stages,
            example_layer_params=parts.block_params["0"],
            fsdp_data_size=fsdp_n,
        )
        embed_specs = (
            embed_module.param_spec() if embed_module is not None
            else jax.tree.map(lambda _: P(), parts.embed_params)
        )
        head_specs = (
            head_module.param_spec() if head_module is not None
            else jax.tree.map(lambda _: P(), parts.head_params)
        )
        # adapters may also live in embed/head trees (e.g. a LoRA'd head)
        embed_specs = lora_spec_tree(embed_specs, parts.embed_params)
        head_specs = lora_spec_tree(head_specs, parts.head_params)
        if fsdp_n > 1:
            embed_specs = fsdp_spec_tree(
                embed_specs, parts.embed_params, fsdp_n
            )
            head_specs = fsdp_spec_tree(head_specs, parts.head_params, fsdp_n)
        self.param_specs = {
            "embed": embed_specs,
            "stages": stacked_specs,
            "head": head_specs,
        }
        self._param_shardings = jax.tree.map(
            lambda s: NamedSharding(mesh, s),
            self.param_specs,
            is_leaf=lambda x: isinstance(x, P),
        )
        self._repl = NamedSharding(mesh, P())
        self._batch_sh = NamedSharding(mesh, P(("data",)))
        self._state_shardings = None  # set in init_state
        self._step_fn = None
        self._eval_fn = None

    # -- state -----------------------------------------------------------
    def init_state(self) -> TrainState:
        params = {
            "embed": self.parts.embed_params,
            "stages": stack_stage_params(self.parts.block_params, self.num_stages),
            "head": self.parts.head_params,
        }
        params = jax.tree.map(
            lambda p, s: jax.device_put(p, s), params, self._param_shardings
        )
        opt_state = self.optimizer.init(params)
        opt_state = jax.device_put(opt_state, self._opt_shardings(opt_state))
        return TrainState(
            params=params, opt_state=opt_state, step=jnp.zeros((), jnp.int32)
        )

    def _opt_shardings(self, opt_state):
        """Optimizer moments shard exactly like their params (free
        ZeRO-style sharding over pipe/model)."""
        return {
            k: self._param_shardings if isinstance(v, dict) else self._repl
            for k, v in opt_state.items()
        }

    def adopt_state(self, state: TrainState) -> TrainState:
        """Adopt a TrainState produced by a trainer on a DIFFERENT mesh
        shape (elastic resume, SURVEY §7.5.4: membership change =>
        re-form mesh + recompile, state carries over). Stage leaves are
        re-factored to this trainer's pipeline depth (reshape_stages)
        and everything is re-placed under this mesh's shardings; embed/
        head/scalars pass through. The checkpoint side needs no mesh
        knowledge — restore host-side, then adopt."""
        S = self.num_stages

        def fix(tree):
            if not isinstance(tree, dict) or "stages" not in tree:
                return tree
            return {
                k: (reshape_stages(v, S) if k == "stages" else v)
                for k, v in tree.items()
            }

        params = fix(state.params)
        opt_state = {k: fix(v) for k, v in state.opt_state.items()}
        params = jax.tree.map(
            lambda p, s: jax.device_put(p, s), params, self._param_shardings
        )
        opt_state = jax.device_put(opt_state, self._opt_shardings(opt_state))
        return TrainState(
            params=params, opt_state=opt_state,
            step=jax.device_put(state.step, self._repl),
        )

    # -- step ------------------------------------------------------------
    def _cast(self, params):
        return jax.tree.map(
            lambda x: x.astype(self.compute_dtype)
            if jnp.issubdtype(x.dtype, jnp.floating)
            else x,
            params,
        )

    def _micro_extras(self, batch, m: int):
        """extras_fn output resliced to [M, mb, ...] leaves (or None)."""
        if self.parts.extras_fn is None:
            return None
        ex = self.parts.extras_fn(batch)
        if ex is None:
            return None
        return jax.tree.map(
            lambda a: a.reshape(m, a.shape[0] // m, *a.shape[1:]), ex
        )

    def _loss(self, params, batch, rng):
        """rng=None -> eval mode (no dropout anywhere)."""
        cfg = self.cfg
        cast = self._cast(params)
        r_embed = r_pipe = r_head = None
        if rng is not None:
            r_embed, r_pipe, r_head = jax.random.split(rng, 3)
        x = self.parts.embed_fn(cast["embed"], batch, rng=r_embed)  # [B, ...]
        B = x.shape[0]
        m = cfg.micro_batches
        if B % m:
            raise ValueError(f"batch {B} not divisible by micro_batches {m}")
        xs = x.reshape(m, B // m, *x.shape[1:])
        extras = self._micro_extras(batch, m)
        if self.aux_weight:
            ys, aux = self.pipeline.apply_with_aux(
                cast["stages"], xs, rng=r_pipe, extras=extras
            )
        else:
            ys = self.pipeline(cast["stages"], xs, rng=r_pipe, extras=extras)
            aux = 0.0
        y = ys.reshape(B, *ys.shape[2:])
        out = self.parts.head_fn(cast, y, batch, rng=r_head)
        return self.loss_fn(out, batch) + self.aux_weight * aux

    def _loss_and_grads_1f1b(self, params, batch, rng):
        """Manual-gradient path: the 1F1B interleave cannot be expressed
        as jax.grad (backwards start mid-forward), so Pipeline1F1B emits
        grads directly; the cast/embed chain is closed by hand (the vjp
        of a dtype cast is the cast back)."""
        cfg = self.cfg
        m = cfg.micro_batches
        r_embed = r_pipe = None
        if rng is not None:
            # SAME split as _loss so embed + block dropout masks stay
            # bitwise-identical across schedules (review finding: a
            # 2-way split here silently diverged every mask). The third
            # key goes unused — the pipe derives per-micro head streams
            # from r_pipe, since 1F1B applies head dropout per micro
            # (GPipe: once over the full batch; masks differ there by
            # construction).
            r_embed, r_pipe, _ = jax.random.split(rng, 3)

        def embed_all(embed_f32):
            ep = self._cast(embed_f32)
            x = self.parts.embed_fn(ep, batch, rng=r_embed)
            B = x.shape[0]
            if B % m:
                raise ValueError(f"batch {B} not divisible by micro_batches {m}")
            return x.reshape(m, B // m, *x.shape[1:])

        xs, embed_vjp = jax.vjp(embed_all, params["embed"])
        cast_stages = self._cast(params["stages"])
        cast_aux = {
            "embed": self._cast(params["embed"]),
            "head": self._cast(params["head"]),
        }
        micro_batches = jax.tree.map(
            lambda a: a.reshape(m, a.shape[0] // m, *a.shape[1:]), batch
        )

        def head_loss(aux_p, y, mb, rng_h):
            out = self.parts.head_fn(
                {"embed": aux_p["embed"], "head": aux_p["head"]}, y, mb, rng=rng_h
            )
            return self.loss_fn(out, mb)

        pipe = Pipeline1F1B(
            self.mesh,
            self.block_fn,
            self.num_stages,
            self.layers_per_stage,
            head_loss,
            block_fn_aux=self.block_fn_aux,
            aux_weight=self.aux_weight,
            seq_axis=self._seq_axis,
        )
        loss, gsp, gaux, dxs = pipe.train_grads(
            cast_stages, cast_aux, xs, micro_batches, rng=r_pipe,
            extras=self._micro_extras(batch, m),
        )
        (dembed,) = embed_vjp(dxs.astype(xs.dtype))
        grads = {
            # tied weights (e.g. GPT-2 lm-head=wte): the head-side
            # contribution from the last stage's vjp adds to the
            # embed_fn-side one
            "embed": jax.tree.map(
                lambda a, b: a + b.astype(a.dtype), dembed, gaux["embed"]
            ),
            "stages": jax.tree.map(
                lambda g, p: g.astype(p.dtype), gsp, params["stages"]
            ),
            "head": jax.tree.map(
                lambda g, p: g.astype(p.dtype), gaux["head"], params["head"]
            ),
        }
        return loss, grads

    def _step(self, state: TrainState, batch, rng):
        if rng is None:
            # deterministic per-step dropout streams without caller plumbing
            rng = jax.random.fold_in(jax.random.key(self.cfg.seed), state.step)
        if self.cfg.pp_schedule == "1f1b":
            loss, grads = self._loss_and_grads_1f1b(state.params, batch, rng)
        else:
            loss, grads = jax.value_and_grad(self._loss)(state.params, batch, rng)
        if self.cfg.train_only == "lora":
            # parameter-efficient fine-tune, inside the SAME sharded
            # program (schedules/axes unchanged). Grads mask BEFORE
            # clipping/optimizer — frozen params must not dominate the
            # clip norm (>99% of it) or accumulate Adam moments — and
            # updates mask again AFTER: AdamW's decoupled weight decay
            # updates params even at zero grad (review finding).
            from tensorlink_tpu.nn.lora import mask_to_lora

            grads = mask_to_lora(grads)
        # non-finite sentinel, BEFORE clipping (an inf leaf turns the
        # clip norm nan and poisons every grad — the flag must name the
        # raw anomaly); mirrors train/trainer.py so skip_nonfinite_updates
        # is honored by BOTH trainers, not silently ignored here
        grads_finite = jax.tree_util.tree_reduce(
            lambda a, g: a & jnp.isfinite(g).all(),
            grads,
            jnp.array(True),
        )
        nonfinite = ~(jnp.isfinite(loss) & grads_finite)
        if self.cfg.grad_clip_norm:
            grads, gnorm = clip_by_global_norm(grads, self.cfg.grad_clip_norm)
        else:
            gnorm = jnp.zeros(())
        updates, opt_state = self.optimizer.update(
            grads, state.opt_state, state.params, state.step
        )
        if self.cfg.train_only == "lora":
            from tensorlink_tpu.nn.lora import mask_to_lora

            updates = mask_to_lora(updates)
        params = apply_updates(state.params, updates)
        new_state = TrainState(
            params=params, opt_state=opt_state, step=state.step + 1
        )
        if self.cfg.skip_nonfinite_updates:
            # select the OLD state wholesale (params, moments, step): a
            # poisoned batch must leave no trace in the model
            new_state = jax.tree.map(
                lambda new, old: jnp.where(nonfinite, old, new),
                new_state,
                state,
            )
        return (
            new_state,
            {"loss": loss, "grad_norm": gnorm, "nonfinite": nonfinite},
        )

    def train_step(self, state: TrainState, batch, rng=None):
        if self._step_fn is None:
            self._step_fn = jax.jit(self._step, static_argnums=(), donate_argnums=(0,))
        batch = jax.device_put(batch, self._batch_sh)
        # telemetry keys on (shape, dtype, rng-variant) — a retrace is
        # labeled compile_step and kept out of the latency histogram
        cm = (
            self._telemetry.step(batch, rng)
            if self._telemetry is not None
            else contextlib.nullcontext()
        )
        # rng=None traces the step-derived-rng variant; an explicit key
        # traces a second variant — both cached by jit.
        # set_mesh makes the trainer's mesh ambient during tracing so
        # modules that pin intermediate shardings on Auto axes (MoE's
        # all_to_all dispatch, nn/moe.py) can engage; everything else is
        # unaffected (all axes here are Auto outside the pipe shard_map).
        with cm, jax.set_mesh(self.mesh):
            state, stats = self._step_fn(state, batch, rng)
        # host-side anomaly accounting rides ONLY the telemetry path —
        # bool() forces a device sync (same tradeoff as train/trainer.py)
        if self._telemetry is not None and bool(stats.get("nonfinite", False)):
            if self.metrics is not None:
                self.metrics.incr("train_nonfinite_total")
            if self.flight is not None:
                self.flight.record(
                    "train_nonfinite",
                    "error",
                    step=int(state.step),
                    loss=float(stats["loss"]),
                    skipped=self.cfg.skip_nonfinite_updates,
                )
        return state, stats

    def eval_fn(self, state: TrainState, batch):
        if self._eval_fn is None:
            self._eval_fn = jax.jit(self._loss)
        with jax.set_mesh(self.mesh):
            return self._eval_fn(state.params, batch, None)

    def audit_programs(self, state: TrainState, batch, rng=None) -> list[dict]:
        """Compiled-program inventory for tlhlo (analysis/hlo.py): the
        fully sharded train step, lowered under the trainer's ambient
        mesh exactly as ``train_step`` traces it. A fresh jit on
        purpose — the lazily-built ``_step_fn`` may belong to a live
        training loop whose trace cache must not see audit avals."""
        donated = len(jax.tree.leaves(state))
        fn = jax.jit(self._step, donate_argnums=(0,))
        sharded_batch = jax.device_put(batch, self._batch_sh)

        def lower():
            with jax.set_mesh(self.mesh):
                return fn.lower(state, sharded_batch, rng)

        return [{
            "name": "step",
            "dtype": str(self.cfg.dtype),
            "donated": donated,
            "lower": lower,
        }]

    # -- reporting ------------------------------------------------------
    @property
    def bubble_fraction(self) -> float:
        return pipeline_bubble_fraction(self.num_stages, self.cfg.micro_batches)

    def measure_bubble(
        self, state, batch, repeats: int = 3, factors: tuple = (1, 2, 3, 4)
    ) -> dict:
        """MEASURED pipeline bubble, not the closed form: time the GPipe
        pipeline forward (the engine's forward path regardless of the
        training schedule — 1F1B's interleave lives in its own grads-only
        program) at k*M micro-batches for each k in ``factors`` (same
        per-micro shape), least-squares fit t = tick_s * (micros + extra):
        the intercept ``extra`` is the measured warmup/drain overhead in
        tick units (ideally S-1), and bubble = extra / (M + extra).

        Multi-point LSQ instead of the round-3 two-point fit: on a noisy
        host a single pair put all variance into the intercept
        (MULTICHIP_r03 recorded 0.78 vs closed-form 0.20 from exactly
        this). The intercept still absorbs fixed per-call dispatch, so
        the fraction is an UPPER bound on the true schedule bubble —
        tight when tick time dominates dispatch; r2 of the fit is
        reported so a noise-dominated measurement is visible. Wall-clock
        is synchronized with a device->host read (block_until_ready does
        not drain the dispatch queue on tunneled runtimes)."""
        import time as _time

        import numpy as _np

        m = self.cfg.micro_batches
        cast = self._cast(state.params)
        x = self.parts.embed_fn(cast["embed"], batch, rng=None)
        B = x.shape[0]
        xs1 = x.reshape(m, B // m, *x.shape[1:])

        if getattr(self, "_bubble_fn", None) is None:
            # cached like _step_fn: a fresh jit closure per call would
            # recompile the pipeline per invocation
            self._bubble_fn = jax.jit(lambda sp, xs: self.pipeline(sp, xs))
        run = self._bubble_fn

        def timed(xs):
            # MIN of per-call times, not the mean: OS-scheduler stalls
            # only ever ADD time, and one stall in the mean was enough to
            # push the 3-point fit's r2 under the 0.95 validity bar on
            # the live r4 run (r2=0.947, measurement discarded). The
            # repeatable minimum is the schedule's actual cost.
            out = run(cast["stages"], xs)
            float(jnp.sum(out[-1]).astype(jnp.float32))  # sync (warmup)
            best = float("inf")
            for _ in range(repeats):
                t0 = _time.perf_counter()
                out = run(cast["stages"], xs)
                float(jnp.sum(out[-1]).astype(jnp.float32))
                best = min(best, _time.perf_counter() - t0)
            return best

        micros = _np.asarray([k * m for k in factors], _np.float64)
        times = _np.asarray(
            [timed(jnp.concatenate([xs1] * k, axis=0)) for k in factors]
        )
        # LSQ t = tick_s * micros + c; extra = c / tick_s
        A = _np.stack([micros, _np.ones_like(micros)], axis=1)
        (tick_s, c), res, *_ = _np.linalg.lstsq(A, times, rcond=None)
        ss_tot = float(((times - times.mean()) ** 2).sum())
        r2 = 1.0 - float(res[0]) / ss_tot if len(res) and ss_tot > 0 else 0.0
        # a 2-point or rank-deficient fit has empty residuals — that is
        # the confident-garbage failure mode this rewrite exists to flag,
        # never a valid measurement
        valid = tick_s > 0 and len(micros) >= 3 and len(res) == 1 and r2 > 0.95
        invalid_reason = None
        if not valid:
            invalid_reason = (
                f"fit rejected: tick_s={tick_s:.3e}, points={len(micros)}, "
                f"residuals={len(res)}, r2={r2:.3f} (need >0.95)"
            )
        # a CPU host with fewer cores than stages SERIALIZES the virtual
        # devices: idle pipeline slots cost no wall time and the bubble
        # is structurally unobservable — whatever lands in the intercept
        # is scheduler noise (a clean r2=0.98 fit measured 0.60 on the
        # r4 dryrun host). Guarded HERE so every caller (bench child,
        # driver dryrun) inherits it; real chips are one device per
        # stage and unaffected.
        dev0 = next(iter(self.mesh.devices.flat))
        if dev0.platform == "cpu":
            import os as _os

            try:
                cores = len(_os.sched_getaffinity(0))
            except AttributeError:  # non-Linux
                cores = _os.cpu_count() or 1
            if cores < self.num_stages:
                valid = False
                invalid_reason = (
                    f"host serializes stages ({cores} cores < "
                    f"{self.num_stages} stages): bubble unobservable; "
                    "closed_form_bubble_fraction is the honest figure"
                )
        extra_ticks = c / tick_s if valid else float("nan")
        measured = (
            extra_ticks / (m + extra_ticks)
            if valid and extra_ticks > 0 else (0.0 if valid else float("nan"))
        )
        return {
            "valid": bool(valid),
            "invalid_reason": invalid_reason,
            "schedule_timed": "gpipe",  # self.pipeline IS the GPipe path
            "micros_timed": [int(v) for v in micros],
            "times_s": [float(t) for t in times],
            "fit_r2": r2,
            "tick_s": float(tick_s),
            "measured_extra_ticks": float(extra_ticks),
            "measured_bubble_fraction": float(measured),
            "closed_form_bubble_fraction": self.bubble_fraction,
            "num_stages": self.num_stages,
            "micro_batches": m,
        }

    def describe(self) -> dict:
        return {
            "mesh": dict(self.mesh.shape),
            "num_stages": self.num_stages,
            "layers_per_stage": self.layers_per_stage,
            "micro_batches": self.cfg.micro_batches,
            "pp_schedule": self.cfg.pp_schedule,
            "bubble_fraction": self.bubble_fraction,
            "dtype": str(self.compute_dtype),
        }
