"""Pipeline-sharded serving: serve models no single worker can hold.

The training path has sliced models across peers since the seed
(``roles/worker.py`` StageRunner); this module brings the same vertical
partitioning to *serving*. The layer stack is cut into contiguous stages
(:func:`tensorlink_tpu.nn.staging.stage_spans`, proportional to each
worker's published HBM), every stage worker runs a **stage-local paged
engine** — its own :class:`~tensorlink_tpu.parallel.kvpool.BlockPool`
holds only that stage's KV blocks — and per-chunk activations ([S, 1, D]
per decode tick, [1, C, D] per prefill chunk — tiny, so ICI-less P2P hops
are affordable exactly where PR 15 showed KV blocks are) stream
worker-to-worker over the native CRC-framed codec via ``ACT_FWD`` /
``ACT_RESULT`` frames in ``p2p/node.py``.

Token parity is an invariant, not a tuning goal: every stage program is a
layer-range restriction of the single-chip paged programs in
``parallel/serving.py`` (same valid-mask update, same write-index
discipline, same logical-coordinate causality), and sampling keys remain
``fold_in(key(seed), position)`` — so an N-stage pipeline emits the exact
token stream a single node with N× the HBM would.

Continuous batching stays live *across* the pipeline: the head
(:class:`PipelineCoordinator`) overlaps decode ticks of resident slots
with prefill chunks of newly admitted ones, so different slots occupy
different stages each tick and stage bubbles are filled by co-resident
traffic (in-flight microbatching).

Failure semantics reuse PR 15's machinery wholesale: typed
``serve_error_to_wire`` errors cross every hop, end-to-end deadlines are
decremented per leg, and a dead stage is survived by validator
re-recruitment of a replica plus **prefix re-prefill** — the head keeps
prompt + accepted tokens host-side, re-prefills them through the repaired
chain, and position-keyed sampling continues the stream without losing or
re-drawing a single accepted token.
"""

from __future__ import annotations

import asyncio
import contextlib
import threading
import time
from collections import OrderedDict, deque
from math import ceil

import jax
import jax.numpy as jnp
import numpy as np

from tensorlink_tpu.nn.staging import StageSlice, layer_param_bytes, stage_spans
from tensorlink_tpu.parallel.inference import (
    GenerationConfig,
    declared_compute_dtype,
    sample_logits,
)
from tensorlink_tpu.parallel.kvpool import BlockPool, PoolExhaustedError
from tensorlink_tpu.parallel.serving import (
    DeadlineExceededError,
    PoolOverloadedError,
    Priority,
    PromptTooLongError,
    QueueFullError,
    ServingError,
    serve_error_from_wire,
)
from tensorlink_tpu.p2p.serialization import pack_arrays, unpack_arrays

__all__ = [
    "ACT_WIRE_SCHEMA",
    "MAX_ACT_BYTES",
    "PipelineCoordinator",
    "PipelineStageEngine",
    "layer_param_bytes",  # re-exported: deployers size stages with these
    "pack_act_payload",
    "plan_pipeline",
    "stage_spans",
    "unpack_act_payload",
]


# --------------------------------------------------------- activation wire
# The activation payload is deliberately minimal: ONE tensor plus a schema
# pin, framed by the same CRC-32C msgpack codec KV blocks ride
# (p2p/serialization.py). All routing/shape metadata travels in the
# ACT_FWD frame's ``meta`` dict where the receiving role's sanitizer can
# clamp it field-by-field (tlproto TLP201).

ACT_WIRE_SCHEMA = 1

# hostile-ingest bound: a decode tick is S*D values and a prefill chunk
# C*D — even a 70B-class stage at fp32 stays well under this; anything
# bigger is a hostile or corrupt frame, not traffic
MAX_ACT_BYTES = 256 << 20


def pack_act_payload(x, codec: str = "zstd") -> bytes:
    """Activation tensor (or sampled-token vector) -> wire blob."""
    return pack_arrays(
        {
            "schema": np.asarray(ACT_WIRE_SCHEMA, np.int32),
            "x": np.asarray(x),
        },
        codec=codec,
    )


def unpack_act_payload(blob) -> np.ndarray:
    """Wire blob -> activation tensor, CRC-checked by the codec and
    schema/size-clamped here (this is the taint sanitizer for peer-fed
    activation payloads — the stage engine still validates exact shape
    against its compiled program before any compute)."""
    if not isinstance(blob, (bytes, bytearray)):
        raise ValueError("activation blob must be bytes")
    if len(blob) > MAX_ACT_BYTES:
        raise ValueError(
            f"activation blob {len(blob)}B exceeds cap {MAX_ACT_BYTES}B"
        )
    arrs = unpack_arrays(bytes(blob))
    schema = int(np.asarray(arrs.get("schema", -1)).reshape(-1)[0])
    if schema != ACT_WIRE_SCHEMA:
        raise ValueError(
            f"activation wire schema {schema} != {ACT_WIRE_SCHEMA} "
            "(incompatible peer build)"
        )
    x = np.asarray(arrs["x"])
    if x.ndim > 3:
        raise ValueError(f"activation rank {x.ndim} > 3")
    return x


# -------------------------------------------------------------- placement
def plan_pipeline(
    fleet: dict[str, dict],
    *,
    n_stages: int | None = None,
    need_bytes: int = 0,
    exclude=(),
) -> dict | None:
    """Pick pipeline stage workers from published capability records.

    Eligibility requires an ``hbm_bytes`` capacity claim (the quantity
    the layer partition is proportional to). Workers are ranked by
    published HBM, roofline decode bandwidth as tiebreak; when
    ``n_stages`` is not forced, the plan takes the FEWEST workers whose
    summed HBM covers ``need_bytes`` — every extra stage is an extra
    per-token wire hop, so depth is a cost, not a goal. Returns ``None``
    when the fleet cannot hold the model at all (the caller renders the
    typed unplaceable error)."""
    exclude = set(exclude or ())
    elig = []
    for nid, cap in (fleet or {}).items():
        if nid in exclude or not isinstance(cap, dict):
            continue
        try:
            hbm = float(cap.get("hbm_bytes") or 0.0)
        except (TypeError, ValueError):
            continue
        if hbm <= 0:
            continue
        try:
            gbps = float(cap.get("hbm_gbps") or 0.0)
        except (TypeError, ValueError):
            gbps = 0.0
        elig.append((nid, hbm, gbps))
    elig.sort(key=lambda t: (-t[1], -t[2], t[0]))
    if n_stages is not None:
        k = int(n_stages)
        if k < 1 or len(elig) < k:
            return None
        pick = elig[:k]
        if need_bytes and sum(h for _, h, _ in pick) < need_bytes:
            return None
    else:
        if need_bytes <= 0:
            raise ValueError("plan_pipeline needs n_stages or need_bytes")
        pick, acc = [], 0.0
        for row in elig:
            pick.append(row)
            acc += row[1]
            if acc >= need_bytes:
                break
        if acc < need_bytes:
            return None
    return {
        "stages": [nid for nid, _, _ in pick],
        "capacities": [h for _, h, _ in pick],
    }


# ----------------------------------------------------------- stage engine
class PipelineStageEngine:
    """One pipeline stage: a layer-range restriction of the paged serving
    programs, over a stage-local block pool.

    Exactly TWO compiled programs per stage (tlhlo TLH105: the pipeline's
    program-count budget scales with stage count and nothing else):

    - ``decode``: one tick for all S slots. Stage 0 embeds the fed
      tokens; every stage runs its layers through its paged KV; the last
      stage applies the head and samples per-slot with the same
      ``fold_in(key(seed), position)`` stream as the single-chip scan.
    - ``prefill_chunk``: one shape-static chunk of one slot, writing
      through the slot's block-table row — the mirror of
      ``PagedContinuousBatchingEngine._build_prefill_chunk`` restricted
      to this stage's layers.

    Host-side slot/admission bookkeeping (block alloc, table ops, retire)
    reuses the paged engine's discipline; prefix caching is deliberately
    NOT wired here (a prefix hit would have to hit on every stage at once
    to be sound — cross-stage prefix coherence is future work)."""

    def __init__(
        self,
        engine,
        *,
        lo: int,
        hi: int,
        sid: str = "pipe",
        stage: int = 0,
        n_stages: int = 1,
        slots: int = 4,
        gen: GenerationConfig | None = None,
        block_size: int = 16,
        num_blocks: int | None = None,
        prefill_chunk: int = 16,
        max_len: int | None = None,
        metrics=None,
        recorder=None,
        capability: dict | None = None,
        **_ignored,
    ):
        self.slice = StageSlice(engine.model, lo, hi)
        self.sid = str(sid)
        self.stage = int(stage)
        self.n_stages = int(n_stages)
        self.slots = int(slots)
        self.gen = gen or GenerationConfig()
        self.L = int(max_len or engine.max_len)
        self.block_size = int(block_size)
        if self.L % self.block_size:
            raise ValueError(
                f"block_size {block_size} must divide the cache view "
                f"width {self.L}"
            )
        self.chunk_len = int(prefill_chunk)
        self.cache_dtype = engine.cache_dtype
        self.metrics = metrics
        self.recorder = recorder
        self.capability = capability
        # the stage holds ONLY its own subtrees — this is what lets a
        # model larger than any one worker's HBM run at all
        self.params = jax.tree.map(
            jax.device_put, self.slice.slice_params(engine.params)
        )
        self.max_blocks = MB = self.L // self.block_size
        nb = num_blocks if num_blocks is not None else self.slots * MB
        self.pool = BlockPool(
            int(nb), self.block_size, metrics=metrics, recorder=recorder
        )
        self._slot_blocks: list[list[int]] = [[] for _ in range(self.slots)]
        caches = self.slice.init_paged_caches(
            self.pool.num_blocks, self.block_size, self.slots, MB,
            dtype=self.cache_dtype,
        )
        self._state = jax.tree.map(jax.device_put, {
            "caches": caches,
            "valid": jnp.zeros((self.slots, self.L), bool),
        })
        self._lock = threading.Lock()
        self._decode_c = None  # AOT-compiled (cost analysis for free)
        self._prefill_c = None
        self._table_op = self._build_table_op()
        self._retire_op = self._build_retire_op()
        self._decode_cost: dict | None = None
        # busy-vs-wall attribution for the per-stage MFU%/BUBBLE%
        # columns in tldiag: busy is device time under this engine's
        # programs, the window is first-to-last activity
        self._busy = {"decode": 0.0, "prefill": 0.0}
        self._steps = {"decode": 0, "prefill": 0}
        self._t_first: float | None = None
        self._t_last: float | None = None

    # ---------------------------------------------------------- programs
    def _build_decode(self):
        sl, S, L = self.slice, self.slots, self.L
        gen = self.gen
        temperature, top_k, top_p = (
            float(gen.temperature), int(gen.top_k), float(gen.top_p)
        )

        def sample_row(seed, n, logits_row):
            key = jax.random.fold_in(jax.random.key(seed), n)
            return sample_logits(logits_row, key, temperature, top_k, top_p)

        def step(params, state, xin, n_valid, live, seeds):
            caches, valid = state["caches"], state["valid"]
            rows = jnp.arange(S)
            index = caches[0]["attn"]["index"]
            # identical to the single-chip scan: the fed token's cache
            # slot becomes attendable for live rows only
            valid = valid.at[rows, index].max(live, mode="drop")
            if sl.first:
                x = sl.embed(params, xin[:, None], n_valid[:, None])
            else:
                x = xin
            x, new_attn = sl.body(
                params, x, [c["attn"] for c in caches],
                mask=valid[:, None, None, :],
                positions=n_valid[:, None],
            )
            new_index = index + live.astype(jnp.int32)
            new_caches = [
                {"attn": {**a, "index": new_index}} for a in new_attn
            ]
            new_state = {"caches": new_caches, "valid": valid}
            if sl.last:
                logits = sl.head(params, x)
                new_n = n_valid + live.astype(jnp.int32)
                nxt = jax.vmap(sample_row)(
                    seeds, new_n, logits[:, -1]
                ).astype(jnp.int32)
                return nxt, new_state
            return x, new_state

        return jax.jit(step, donate_argnums=(1,))

    def _build_prefill_chunk(self):
        sl, L, C = self.slice, self.L, self.chunk_len
        gen = self.gen
        temperature, top_k, top_p = (
            float(gen.temperature), int(gen.top_k), float(gen.top_p)
        )

        def chunk(params, state, xin, slot, start, nreal, seed):
            caches = state["caches"]
            tmp = [
                {
                    "k": lc["attn"]["k"],
                    "v": lc["attn"]["v"],
                    "index": jnp.full((1,), start, jnp.int32),
                    "block_table": jax.lax.dynamic_slice_in_dim(
                        lc["attn"]["block_table"], slot, 1, axis=0
                    ),
                }
                for lc in caches
            ]
            positions = (start + jnp.arange(C))[None, :]
            if sl.first:
                x = sl.embed(params, xin, positions)
            else:
                x = xin
            # mask=None: the paged attention path builds causality in
            # logical coordinates — exactly the single-chip chunk
            x, new_tmp = sl.body(
                params, x, tmp, mask=None, positions=positions
            )
            new_caches = [
                {"attn": {
                    "k": nt["k"],
                    "v": nt["v"],
                    "index": lc["attn"]["index"].at[slot].set(start + nreal),
                    "block_table": lc["attn"]["block_table"],
                }}
                for lc, nt in zip(caches, new_tmp)
            ]
            n_end = start + nreal
            new_state = {
                "caches": new_caches,
                "valid": state["valid"].at[slot].set(jnp.arange(L) < n_end),
            }
            if sl.last:
                logits = sl.head(params, x)
                last = jax.lax.dynamic_index_in_dim(
                    logits[0], nreal - 1, axis=0, keepdims=False
                )
                key0 = jax.random.fold_in(jax.random.key(seed), n_end)
                tok0 = sample_logits(
                    last, key0, temperature, top_k, top_p
                ).astype(jnp.int32)
                return tok0, new_state
            return x, new_state

        return jax.jit(chunk, donate_argnums=(1,))

    def _build_table_op(self):
        def run(state, slot, row):
            new_caches = [
                {"attn": {
                    **lc["attn"],
                    "index": lc["attn"]["index"].at[slot].set(0),
                    "block_table": lc["attn"]["block_table"].at[slot].set(
                        row
                    ),
                }}
                for lc in state["caches"]
            ]
            return {**state, "caches": new_caches}

        return jax.jit(run, donate_argnums=(0,))

    def _build_retire_op(self):
        NB, MB, L = self.pool.num_blocks, self.max_blocks, self.L

        def run(state, slot):
            new_caches = [
                {"attn": {
                    **lc["attn"],
                    "block_table": lc["attn"]["block_table"].at[slot].set(
                        jnp.full((MB,), NB, jnp.int32)
                    ),
                }}
                for lc in state["caches"]
            ]
            return {
                **state,
                "caches": new_caches,
                "valid": state["valid"].at[slot].set(jnp.zeros((L,), bool)),
            }

        return jax.jit(run, donate_argnums=(0,))

    # -------------------------------------------------------------- host
    def _note(self, tag: str, dt: float) -> None:
        now = time.perf_counter()
        self._busy[tag] += dt
        self._steps[tag] += 1
        if self._t_first is None:
            self._t_first = now - dt
        self._t_last = now

    def begin_request(self, slot: int, n_ctx: int, budget: int) -> None:
        """Admit (or re-admit) a request into ``slot``: release the
        previous tenant's blocks, allocate enough for prompt + budget
        up front, point the slot's block-table row at them. Upfront
        allocation keeps the decode tick free of growth ops — the wire
        already serializes ticks, so admission is the only place the
        pool is touched."""
        slot = int(slot)
        n_ctx, budget = int(n_ctx), int(budget)
        if not (0 <= slot < self.slots):
            raise ValueError(f"slot {slot} out of range")
        if n_ctx < 1 or n_ctx + budget > self.L:
            raise PromptTooLongError(
                f"prompt {n_ctx} + budget {budget} exceeds cache view "
                f"width {self.L}"
            )
        nblocks = ceil(min(n_ctx + budget, self.L) / self.block_size)
        with self._lock:
            for bid in self._slot_blocks[slot]:
                self.pool.release(bid)
            self._slot_blocks[slot] = []
            try:
                blocks = self.pool.alloc(nblocks)
            except PoolExhaustedError as e:
                raise PoolOverloadedError(
                    f"stage {self.stage} pool exhausted: {e}"
                ) from e
            self._slot_blocks[slot] = blocks
            row = np.full((self.max_blocks,), self.pool.num_blocks, np.int32)
            row[: len(blocks)] = blocks
            self._state = self._table_op(
                self._state, jnp.int32(slot), jnp.asarray(row)
            )

    def slot_blocks(self, slot: int) -> int:
        """How many pool blocks ``slot`` currently pins (metering reads
        this for the KV block-seconds rectangle; upfront allocation at
        admission means it is constant over a request's residency)."""
        with self._lock:
            return len(self._slot_blocks[int(slot)])

    def release_slot(self, slot: int) -> None:
        slot = int(slot)
        with self._lock:
            for bid in self._slot_blocks[slot]:
                self.pool.release(bid)
            self._slot_blocks[slot] = []
            self._state = self._retire_op(self._state, jnp.int32(slot))

    def reset_all(self) -> None:
        for s in range(self.slots):
            self.release_slot(s)

    def _expect_x(self, x: np.ndarray, shape: tuple, dtype) -> jnp.ndarray:
        x = jnp.asarray(x)
        if tuple(x.shape) != shape:
            raise ValueError(
                f"activation shape {tuple(x.shape)} != expected {shape}"
            )
        return x.astype(dtype)

    def _act_dtype(self):
        return jnp.asarray(
            jax.tree.leaves(self.params["blocks"])[0]
        ).dtype

    def prefill_chunk(self, slot, xin, start, nreal, seed,
                      n_ctx=None, budget=None):
        """Run one prefill chunk for ``slot``. On the first chunk
        (``start == 0``) the slot is (re)admitted with ``n_ctx``/
        ``budget``. Returns the stage output as a host array: hidden
        states for relaying stages, the sampled first token for the
        last stage (meaningful only on the final chunk — identical to
        the single-chip program, which also samples every chunk and
        lets the host keep only the last draw)."""
        slot, start, nreal = int(slot), int(start), int(nreal)
        C = self.chunk_len
        if not (1 <= nreal <= C) or start < 0 or start + nreal > self.L:
            raise ValueError("prefill chunk out of bounds")
        if start == 0:
            if n_ctx is None or budget is None:
                raise ValueError("first chunk needs n_ctx and budget")
            self.begin_request(slot, n_ctx, budget)
        if self.slice.first:
            x = self._expect_x(xin, (1, C), None).astype(jnp.int32)
        else:
            x = self._expect_x(
                xin, (1, C, self.slice.hidden_dim), self._act_dtype()
            )
        with self._lock:
            args = (
                self.params, self._state, x, jnp.int32(slot),
                jnp.int32(start), jnp.int32(nreal), jnp.uint32(seed),
            )
            t0 = time.perf_counter()
            if self._prefill_c is None:
                self._prefill_c = self._build_prefill_chunk()
            out, self._state = self._prefill_c(*args)
            out = np.asarray(out)
            self._note("prefill", time.perf_counter() - t0)
        return out

    def decode_step(self, xin, n_valid, live, seeds):
        """One decode tick across all S slots. ``xin`` is the fed token
        vector [S] on stage 0 and the upstream hidden states [S, 1, D]
        elsewhere; ``n_valid``/``live``/``seeds`` ride the wire from the
        head so every stage computes with identical row state. Returns
        hidden states (relay stages) or sampled tokens [S] (last)."""
        S = self.slots
        n_valid = np.asarray(n_valid, np.int32)
        live = np.asarray(live, bool)
        seeds = np.asarray(seeds, np.uint32)
        if n_valid.shape != (S,) or live.shape != (S,) or seeds.shape != (S,):
            raise ValueError("decode row-state arrays must be [slots]")
        if self.slice.first:
            x = self._expect_x(xin, (S,), None).astype(jnp.int32)
        else:
            x = self._expect_x(
                xin, (S, 1, self.slice.hidden_dim), self._act_dtype()
            )
        with self._lock:
            args = (
                self.params, self._state, x, jnp.asarray(n_valid),
                jnp.asarray(live), jnp.asarray(seeds),
            )
            t0 = time.perf_counter()
            if self._decode_c is None:
                self._decode_c = self._build_decode()
                self._capture_decode_cost(args)
            out, self._state = self._decode_c(*args)
            out = np.asarray(out)
            self._note("decode", time.perf_counter() - t0)
        return out

    def _capture_decode_cost(self, args) -> None:
        """Opportunistic XLA cost analysis for the decode tick — the
        flops behind the per-stage MFU% column. Advisory: not every
        backend reports."""
        try:
            cost = self._decode_c.lower(*args).compile().cost_analysis()
            if isinstance(cost, (list, tuple)):
                cost = cost[0]
            rec = {}
            if cost.get("flops"):
                rec["flops"] = float(cost["flops"])
            if cost.get("bytes accessed"):
                rec["bytes"] = float(cost["bytes accessed"])
            self._decode_cost = rec or None
        except Exception:  # noqa: BLE001 — telemetry must not fail serving
            self._decode_cost = None

    # ------------------------------------------------------------- audit
    def audit_programs(self) -> list[dict]:
        """Compiled-program inventory for tlhlo: ONE decode + ONE
        prefill program per stage (the TLH105 pipeline budget). Fresh
        jits lowered from avals — nothing executes or touches the
        donated live state."""
        dt = declared_compute_dtype(self.params)
        with self._lock:
            donated = len(jax.tree.leaves(self._state))
            state_sds = jax.tree.map(
                lambda a: jax.ShapeDtypeStruct(a.shape, a.dtype),
                self._state,
            )
        S, C, D = self.slots, self.chunk_len, self.slice.hidden_dim
        sds = jax.ShapeDtypeStruct
        i32, u32 = jnp.int32, jnp.uint32
        act = jnp.dtype(self._act_dtype())
        dec_x = sds((S,), i32) if self.slice.first else sds((S, 1, D), act)
        pre_x = sds((1, C), i32) if self.slice.first else sds((1, C, D), act)

        def lower_decode():
            return self._build_decode().lower(
                self.params, state_sds, dec_x, sds((S,), i32),
                sds((S,), jnp.bool_), sds((S,), u32),
            )

        def lower_prefill():
            return self._build_prefill_chunk().lower(
                self.params, state_sds, pre_x, sds((), i32), sds((), i32),
                sds((), i32), sds((), u32),
            )

        return [
            {"name": "decode", "dtype": dt, "donated": donated,
             "lower": lower_decode},
            {"name": "prefill_chunk", "dtype": dt, "donated": donated,
             "lower": lower_prefill},
        ]

    # ------------------------------------------------------------- stats
    def stats(self) -> dict:
        with self._lock:
            busy_d, busy_p = self._busy["decode"], self._busy["prefill"]
            steps_d, steps_p = self._steps["decode"], self._steps["prefill"]
            t_first, t_last = self._t_first, self._t_last
            cost = self._decode_cost
        busy = busy_d + busy_p
        window = 0.0
        if t_first is not None and t_last is not None:
            window = max(t_last - t_first, 0.0)
        bubble = max(0.0, 1.0 - busy / window) if window > 1e-9 else 0.0
        out = {
            "pipeline_stage": self.stage,
            "pipeline_n_stages": self.n_stages,
            "layers": [self.slice.lo, self.slice.hi],
            "decode_steps": steps_d,
            "prefill_chunks": steps_p,
            "decode_s": round(busy_d, 6),
            "prefill_s": round(busy_p, 6),
            "busy_s": round(busy, 6),
            "window_s": round(window, 6),
            "bubble_frac": round(bubble, 4),
            "pool": self.pool.stats(),
        }
        mfu = self._mfu_from(cost, busy_d, steps_d)
        if mfu is not None:
            out["mfu"] = mfu
        return out

    def stage_mfu(self) -> float | None:
        """Measured decode MFU against the published roofline — the
        tldiag per-stage MFU% column. None when the backend reports no
        flops or no capability was measured."""
        with self._lock:
            cost = self._decode_cost
            busy, n = self._busy["decode"], self._steps["decode"]
        return self._mfu_from(cost, busy, n)

    def _mfu_from(
        self, cost: dict | None, busy: float, n: int,
    ) -> float | None:
        peak = (self.capability or {}).get("peak_tflops")
        if not cost or not cost.get("flops") or not peak or busy <= 0:
            return None
        return round(
            (cost["flops"] * n / busy) / (float(peak) * 1e12), 6
        )


# ------------------------------------------------------------ coordinator
class PipelineCoordinator:
    """Head-of-pipeline scheduler (runs on the stage-0 worker).

    Duck-types the serving-engine surface the worker's SERVE_SUBMIT /
    SERVE_RESULT handlers and :class:`RemoteServingClient` already speak
    — ``asubmit`` / ``aresult`` / ``stats`` / ``pool`` — so the entire
    PR 15 client path works against a pipeline unchanged.

    Per decode tick: run the local stage-0 program over ALL slots, ship
    the [S, 1, D] hidden states down the chain as one ``ACT_FWD`` whose
    reply (the last stage's sampled tokens) relays back up, then apply
    EOS/budget bookkeeping host-side. Prefill streams chunk-by-chunk the
    same way. Admissions overlap in-flight ticks (asyncio.gather), so a
    newly admitted request's prefill chunks occupy early stages while
    resident slots' decode traffic occupies later ones."""

    ACT_TIMEOUT_S = 60.0

    def __init__(
        self,
        node,
        engine: PipelineStageEngine,
        *,
        route: list[dict],
        sid: str,
        validator=None,
        max_queue: int = 64,
        gen: GenerationConfig | None = None,
    ):
        self.node = node
        self.engine = engine
        self.route = [dict(w) for w in (route or [])]
        self.sid = str(sid)
        self.n_stages = len(self.route) + 1
        self.validator = validator
        self.gen = gen or engine.gen
        self.max_queue = int(max_queue)
        self.slots = engine.slots
        self.L = engine.L
        self._requests: dict[int, dict] = {}
        self._slot_rid: list[int | None] = [None] * self.slots
        self._queue: list[int] = []
        self._next_rid = 1
        self._wake = asyncio.Event()
        self._pump_task: asyncio.Task | None = None
        self._opened = False
        self._ticks = 0
        self._act_bytes = 0
        self._failovers = 0
        self._refills = 0
        # per-request resource metering (runtime/ledger.py): the head
        # owns the whole request lifecycle, so it is the one place a
        # pipeline request's stage-0 busy seconds, activation wire
        # bytes, and KV block-seconds can be folded into ONE meter the
        # stage-0 worker signs (kind="pipeline"). Downstream stages'
        # device time is deliberately NOT claimed — a receipt only ever
        # bills work the signing node itself performed.
        self.metering = True
        self.meter_kind = "pipeline"
        self._meter_log: OrderedDict[int, dict] = OrderedDict()
        self._meter_fresh: deque = deque(maxlen=512)
        self._metered_total = 0

    # ------------------------------------------------------------ spans
    def _span(self, name: str, req: dict | None = None, **attrs):
        """Child span of a request's ``serving.pipeline_request`` root
        (or of the current task's span, for hop spans opened inside a
        prefill/tick span). No tracer on the node -> no-op."""
        tracer = getattr(self.node, "tracer", None)
        if tracer is None:
            return contextlib.nullcontext()
        root = (req or {}).get("span")
        remote = root.context() if root is not None else None
        return tracer.span(name, attrs=attrs, remote=remote)

    # expose the stage-0 pool so capability records advertise real
    # KV headroom for this node's share of the pipeline
    @property
    def pool(self):
        return self.engine.pool

    # ------------------------------------------------------------ submit
    async def asubmit(
        self, ids, *, max_new: int | None = None, seed: int = 0,
        priority=Priority.STANDARD, deadline_s: float | None = None,
        tenant: str | None = None,
    ) -> int:
        ids = [int(t) for t in np.asarray(ids).reshape(-1)]
        max_new = int(max_new if max_new is not None else
                      self.gen.max_new_tokens)
        if not ids:
            raise ServingError("empty prompt")
        if len(ids) + max_new > self.L:
            raise PromptTooLongError(
                f"prompt {len(ids)} + max_new {max_new} exceeds pipeline "
                f"cache view width {self.L}"
            )
        if len(self._queue) >= self.max_queue:
            raise QueueFullError(
                f"pipeline admission queue full ({self.max_queue})",
                retry_after_s=1.0,
            )
        rid = self._next_rid
        self._next_rid += 1
        req = {
            "rid": rid, "ids": ids, "max_new": max_new,
            "seed": int(seed) & 0xFFFFFFFF,
            "deadline_at": (
                time.perf_counter() + float(deadline_s)
                if deadline_s is not None else None
            ),
            "tokens": [], "state": "queued", "slot": None,
            "last_tok": 0, "n_valid": 0,
            "done": asyncio.Event(), "error": None,
            # metering accumulators + wall anchors (runtime/ledger.py)
            "tenant": str(tenant)[:128] if tenant else None,
            "t_wall0": time.time(), "t0": time.perf_counter(),
            "busy_s": 0.0, "wire_bytes": 0.0,
            "kv_blocks": 0, "kv_anchor": None, "kv_block_s": 0.0,
            "span": None,
        }
        tracer = getattr(self.node, "tracer", None)
        if tracer is not None:
            # root of this request's timeline: prefill chunks, decode
            # ticks, and chain hops open as its children, and the
            # downstream stages' handler spans continue the same trace
            # over the wire — /spans on any stage shows the stitched
            # per-stage view
            req["span"] = tracer.start_span(
                "serving.pipeline_request",
                {"sid": self.sid, "rid": rid, "prompt_len": len(ids),
                 "max_new": max_new, "n_stages": self.n_stages},
            )
        self._requests[rid] = req
        self._queue.append(rid)
        self._ensure_pump()
        return rid

    async def aresult(
        self, rid: int, *, timeout_s: float | None = None,
        deadline_s: float | None = None,
    ) -> list[int]:
        req = self._requests.get(int(rid))
        if req is None:
            raise ServingError(f"unknown rid {rid}")
        wait = timeout_s if timeout_s is not None else deadline_s
        try:
            if wait is None:
                await req["done"].wait()
            else:
                await asyncio.wait_for(req["done"].wait(), float(wait))
        except asyncio.TimeoutError:
            if deadline_s is not None and timeout_s is None:
                self._fail(req, DeadlineExceededError(
                    f"rid {rid} missed its result deadline", rid=rid
                ))
            else:
                # soft timeout: the stream is still running and
                # collectable by a later poll — typed so the client
                # can tell this from a dead leg
                raise TimeoutError(
                    f"rid {rid} still decoding after {wait}s"
                ) from None
        if req["error"] is not None:
            self._requests.pop(int(rid), None)
            raise req["error"]
        self._requests.pop(int(rid), None)
        return list(req["tokens"])

    # -------------------------------------------------------------- pump
    def _ensure_pump(self) -> None:
        self._wake.set()
        if self._pump_task is None or self._pump_task.done():
            self._pump_task = asyncio.get_running_loop().create_task(
                self._pump()
            )

    def _active(self) -> list[dict]:
        return [
            self._requests[r] for r in self._slot_rid
            if r is not None and r in self._requests
        ]

    async def open(self) -> None:
        """Geometry handshake: PIPE_LOAD every downstream stage and
        verify sid/slot-count/cache-width/layer-contiguity before any
        activation crosses the wire."""
        if self._opened:
            return
        want_lo = self.engine.slice.hi
        for i in range(1, self.n_stages):
            peer = await self._stage_peer(i)
            resp = await self.node.request(peer, {
                "type": "PIPE_LOAD", "sid": self.sid, "stage": i,
                "n_stages": self.n_stages, "slots": self.slots,
                "max_len": self.L, "reset": False,
            })
            self._check_act(resp, "PIPE_LOAD")
            if int(resp.get("lo", -1)) != want_lo:
                raise ServingError(
                    f"stage {i} layers [{resp.get('lo')}, "
                    f"{resp.get('hi')}) do not continue [.., {want_lo})"
                )
            want_lo = int(resp.get("hi", -1))
        if want_lo != self.engine.slice.num_layers:
            raise ServingError(
                f"pipeline covers layers up to {want_lo} of "
                f"{self.engine.slice.num_layers}"
            )
        self._opened = True

    async def _pump(self) -> None:
        while True:
            try:
                if not self._opened:
                    await self.open()
            except Exception as e:  # noqa: BLE001 — typed + transport
                self._fail_all(e)
                return
            self._expire_deadlines()
            admits = []
            while self._queue and None in self._slot_rid:
                rid = self._queue.pop(0)
                req = self._requests.get(rid)
                if req is None:
                    continue
                slot = self._slot_rid.index(None)
                self._slot_rid[slot] = rid
                req["slot"] = slot
                req["state"] = "prefill"
                admits.append(req)
            decoding = [
                r for r in self._active() if r["state"] == "decoding"
            ]
            tasks = []
            if decoding:
                tasks.append(self._tick(decoding))
            tasks.extend(self._prefill(r) for r in admits)
            if not tasks:
                if not self._queue and not self._active():
                    return  # idle: next asubmit restarts the pump
                self._wake.clear()
                await self._wake.wait()
                continue
            results = await asyncio.gather(*tasks, return_exceptions=True)
            for err in results:
                if isinstance(err, Exception):
                    await self._handle_chain_error(err)

    def _expire_deadlines(self) -> None:
        now = time.perf_counter()
        for req in list(self._requests.values()):
            da = req["deadline_at"]
            if da is not None and now > da and not req["done"].is_set():
                self._fail(req, DeadlineExceededError(
                    f"rid {req['rid']} deadline passed mid-pipeline",
                    rid=req["rid"],
                ))

    # ------------------------------------------------------------- legs
    async def _stage_peer(self, i: int):
        winfo = self.route[i - 1]
        p = self.node.peers.get(winfo["node_id"])
        if p is not None:
            return p
        return await self.node.connect_candidates(
            winfo["host"], int(winfo["port"]),
            tuple(winfo.get("alt_hosts", ()) or ()),
            expect_id=winfo["node_id"],
        )

    @staticmethod
    def _check_act(resp: dict, want: str) -> dict:
        if isinstance(resp, dict) and resp.get("type") == "SERVE_FAILED":
            e = serve_error_from_wire(resp)
            e.dead_stage = resp.get("dead_stage")
            e.dead_node = resp.get("dead_node")
            raise e
        if not isinstance(resp, dict) or resp.get("type") != want:
            raise ServingError(
                f"pipeline hop replied "
                f"{resp.get('type') if isinstance(resp, dict) else resp!r}, "
                f"wanted {want}"
            )
        return resp

    async def _chain(self, out, meta: dict, bill=()) -> dict:
        """Ship a stage-0 output down the chain; the last stage's
        ACT_RESULT relays back as this request's reply. Transport
        failures on the FIRST hop are tagged dead_stage=1 here; deeper
        hops tag themselves in their typed relay error. ``bill`` lists
        the request dicts whose meters split this hop's wire bytes."""
        blob = await asyncio.to_thread(pack_act_payload, out)
        self._act_bytes += len(blob)
        if self.metering and bill:
            share = len(blob) / len(bill)
            for r in bill:
                r["wire_bytes"] += share
        route_rest = [
            {k: w[k] for k in ("node_id", "host", "port") if k in w}
            | {"alt_hosts": list(w.get("alt_hosts", ()) or [])}
            for w in self.route[1:]
        ]
        meta = {
            **meta, "sid": self.sid, "stage": 1, "route": route_rest,
        }
        try:
            # the hop span parents under the enclosing prefill/tick
            # span (same coroutine), so each chain crossing shows up
            # on the request timeline with its payload size
            with self._span(
                "serving.pipeline.hop", None,
                stage=1, kind=str(meta.get("kind")), bytes=len(blob),
            ):
                peer = await self._stage_peer(1)
                resp = await self.node.send_activations(
                    peer, blob, meta, timeout=self.ACT_TIMEOUT_S
                )
        except (ConnectionError, OSError, asyncio.TimeoutError,
                TimeoutError) as e:
            err = ServingError(f"pipeline stage 1 unreachable: {e}")
            err.dead_stage = 1
            err.dead_node = self.route[0].get("node_id")
            raise err from e
        return self._check_act(resp, "ACT_RESULT")

    def _leg_deadline(self, reqs) -> float | None:
        das = [r["deadline_at"] for r in reqs if r["deadline_at"]]
        if not das:
            return None
        return max(0.001, min(das) - time.perf_counter())

    async def _prefill(self, req: dict) -> None:
        """Stream one request's prompt (plus, after a failover, its
        already-accepted tokens) through the pipeline chunk-by-chunk.
        The final chunk's relayed ``tok0`` is the next token of the
        stream — sampled at logical position ``len(ids_eff)``, exactly
        where the single-chip program would draw it."""
        try:
            eng = self.engine
            ids_eff = req["ids"] + req["tokens"]
            budget = req["max_new"] - len(req["tokens"])
            if budget <= 0:
                self._finish(req)
                return
            n, C, slot = len(ids_eff), eng.chunk_len, req["slot"]
            tok0 = None
            with self._span(
                "serving.pipeline.prefill", req,
                stage=0, slot=slot, n_ctx=n,
            ):
                for start in range(0, n, C):
                    da = req["deadline_at"]
                    if da is not None and time.perf_counter() > da:
                        raise DeadlineExceededError(
                            f"rid {req['rid']} deadline passed during "
                            "prefill", rid=req["rid"],
                        )
                    nreal = min(C, n - start)
                    ids_chunk = np.zeros((1, C), np.int32)
                    ids_chunk[0, :nreal] = ids_eff[start:start + nreal]
                    tb = time.perf_counter()
                    out = await asyncio.to_thread(
                        eng.prefill_chunk, slot, ids_chunk, start, nreal,
                        req["seed"], n, budget,
                    )
                    if self.metering:
                        req["busy_s"] += time.perf_counter() - tb
                        if start == 0:
                            # upfront allocation: the block count is
                            # fixed for the slot's whole residency, so
                            # the KV rectangle is one anchor + one close
                            req["kv_blocks"] = eng.slot_blocks(slot)
                            req["kv_anchor"] = time.perf_counter()
                    if self.n_stages == 1:
                        tok0 = int(out)
                        continue
                    resp = await self._chain(out, {
                        "kind": "prefill", "slot": slot, "start": start,
                        "nreal": nreal, "seed": req["seed"], "n_ctx": n,
                        "budget": budget,
                        "deadline_s": self._leg_deadline([req]),
                    }, bill=(req,))
                    tok0 = int(resp["tok0"])
            req["n_valid"] = n
            req["tokens"].append(tok0)
            req["last_tok"] = tok0
            req["n_valid"] += 1
            eos = self.gen.eos_token_id
            if budget <= 1 or (eos is not None and tok0 == eos):
                self._finish(req)
            else:
                req["state"] = "decoding"
        except (ServingError, TimeoutError) as e:
            if getattr(e, "dead_stage", None) is not None:
                raise  # chain death: let the pump run failover
            self._fail(req, e)

    async def _tick(self, decoding: list[dict]) -> None:
        """One pipeline decode tick for every decoding slot at once —
        the in-flight microbatch."""
        from tensorlink_tpu.runtime import chaos

        if chaos.ACTIVE is not None and chaos.ACTIVE.apply_sync(
            "pipeserve.tick", tick=self._ticks, sid=self.sid
        ):
            return  # chaos drop: skip this tick, state untouched
        eng = self.engine
        S = self.slots
        toks = np.zeros((S,), np.int32)
        n_valid = np.zeros((S,), np.int32)
        live = np.zeros((S,), bool)
        seeds = np.zeros((S,), np.uint32)
        for req in decoding:
            s = req["slot"]
            toks[s] = req["last_tok"]
            # the fed token occupies position n_valid - 1; the decode
            # program is fed the SEQUENCE length before this tick's
            # token, i.e. the single-chip state's n_valid
            n_valid[s] = req["n_valid"] - 1
            live[s] = True
            seeds[s] = req["seed"]
        with self._span(
            "serving.pipeline.decode_tick", decoding[0],
            stage=0, tick=self._ticks, rows=len(decoding),
        ):
            tb = time.perf_counter()
            out = await asyncio.to_thread(
                eng.decode_step, toks, n_valid, live, seeds
            )
            if self.metering:
                # one program run serves every live row: each slot
                # bills for the batch lane it held this tick
                share = (time.perf_counter() - tb) / len(decoding)
                for req in decoding:
                    req["busy_s"] += share
            if self.n_stages > 1:
                resp = await self._chain(out, {
                    "kind": "decode", "tick": self._ticks,
                    "n_valid": n_valid.tolist(),
                    "live": live.tolist(),
                    "seeds": seeds.tolist(),
                    "deadline_s": self._leg_deadline(decoding),
                }, bill=decoding)
                tokens = np.asarray(resp["tokens"], np.int64)
                if tokens.shape != (S,):
                    raise ServingError(
                        f"pipeline tick returned {tokens.shape} tokens, "
                        f"wanted ({S},)"
                    )
            else:
                tokens = np.asarray(out, np.int64)
        self._ticks += 1
        eos = self.gen.eos_token_id
        for req in decoding:
            tok = int(tokens[req["slot"]])
            req["tokens"].append(tok)
            req["last_tok"] = tok
            req["n_valid"] += 1
            remaining = req["max_new"] - len(req["tokens"])
            if remaining <= 0 or (eos is not None and tok == eos):
                self._finish(req)

    # ---------------------------------------------------------- failover
    async def _handle_chain_error(self, err: Exception) -> None:
        dead = getattr(err, "dead_stage", None)
        if dead is None:
            # a typed per-request error already handled in _prefill, or
            # a local fault: fail everything in flight loudly
            self._fail_all(err)
            return
        ok = await self._failover(int(dead), getattr(err, "dead_node", None))
        if not ok:
            self._fail_all(ServingError(
                f"pipeline stage {dead} died and no replacement is "
                f"available ({err})"
            ))

    async def _failover(self, dead_stage: int, dead_node) -> bool:
        """Survive a dead stage: validator re-recruits a replica worker
        already holding the same stage slice, every downstream stage
        resets, and the head re-prefills prompt + accepted tokens for
        each in-flight request through the repaired chain. Accepted
        tokens are never re-sampled — position-keyed sampling continues
        the stream exactly."""
        self._failovers += 1
        node = self.node
        if getattr(node, "flight", None) is not None:
            node.flight.record(
                "serving.pipeline_failover", "warn", sid=self.sid,
                stage=dead_stage, dead=str(dead_node)[:64],
            )
        if self.validator is None:
            return False
        # re-resolve the validator handle: the stored Peer may be stale
        # (a later inbound dial from the validator displaces the
        # outbound stream in _register_peer) — the registry holds the
        # LIVE connection under the same node_id
        validator = node.peers.get(
            getattr(self.validator, "node_id", None)
        ) or self.validator
        try:
            resp = await node.request(validator, {
                "type": "SERVE_PIPELINE_PLAN", "sid": self.sid,
                "stage": int(dead_stage),
                "exclude": [dead_node] if dead_node else [],
            })
        except (ConnectionError, OSError, asyncio.TimeoutError) as e:
            if getattr(node, "flight", None) is not None:
                node.flight.record(
                    "serving.pipeline_failover_failed", "error",
                    sid=self.sid, stage=dead_stage, error=str(e)[:120],
                )
            return False
        if not isinstance(resp, dict) or resp.get("error") or \
                not resp.get("node"):
            return False
        winfo = resp["node"]
        old = self.route[dead_stage - 1]
        self.route[dead_stage - 1] = dict(winfo)
        # a stale peer handle to the dead node must not be reused
        self.node.peers.pop(old.get("node_id"), None)
        self._opened = False
        try:
            # re-handshake (PIPE_LOAD) then hard-reset every stage's
            # slots — re-prefill rebuilds all KV from scratch
            await self.open()
            for i in range(1, self.n_stages):
                peer = await self._stage_peer(i)
                resp = await self.node.request(peer, {
                    "type": "PIPE_LOAD", "sid": self.sid, "stage": i,
                    "n_stages": self.n_stages, "slots": self.slots,
                    "max_len": self.L, "reset": True,
                })
                self._check_act(resp, "PIPE_LOAD")
            await asyncio.to_thread(self.engine.reset_all)
            for req in self._active():
                if req["done"].is_set():
                    continue
                self._refills += 1
                req["state"] = "prefill"
                await self._prefill(req)
        except (ServingError, TimeoutError, ConnectionError, OSError,
                asyncio.TimeoutError) as e:
            if getattr(node, "flight", None) is not None:
                node.flight.record(
                    "serving.pipeline_failover_failed", "error",
                    sid=self.sid, stage=dead_stage, error=str(e)[:120],
                )
            return False
        if getattr(node, "flight", None) is not None:
            node.flight.record(
                "serving.pipeline_failover_done", "info", sid=self.sid,
                stage=dead_stage, replacement=str(
                    winfo.get("node_id"))[:16],
            )
        return True

    # ------------------------------------------------------- bookkeeping
    def _finish(self, req: dict) -> None:
        self._release(req)
        req["state"] = "done"
        self._meter_finish(req)
        self._finish_span(req, "ok")
        req["done"].set()

    def _fail(self, req: dict, err: Exception) -> None:
        self._release(req)
        req["state"] = "failed"
        req["error"] = err
        self._finish_span(req, "error")
        req["done"].set()

    def _finish_span(self, req: dict, status: str) -> None:
        sp = req.pop("span", None)
        if sp is not None:
            tracer = getattr(self.node, "tracer", None)
            if tracer is not None:
                tracer.finish_span(sp, status=status)

    def _meter_finish(self, req: dict) -> None:
        """Freeze this request's meter for receipt signing (successful
        completions only — a failed stream delivered nothing billable).
        The worker's ``work_receipt``/``pending_receipts`` read these
        through the same ``meter``/``drain_meters`` surface the flat
        engines expose."""
        if not self.metering:
            return
        t0 = req.get("t_wall0") or time.time()
        meter = {
            "rid": int(req["rid"]),
            "tenant": req.get("tenant"),
            "kind": self.meter_kind,
            "t_start": float(t0),
            "t_end": float(
                t0 + max(time.perf_counter() - req.get("t0", 0.0), 0.0)
            ) if req.get("t0") else float(t0),
            "prompt_tokens": len(req["ids"]),
            "emitted_tokens": len(req["tokens"]),
            "busy_s": float(req.get("busy_s", 0.0)),
            "flops": 0.0,
            "hbm_bytes": 0.0,
            "kv_block_s": float(req.get("kv_block_s", 0.0)),
            "wire_bytes": int(req.get("wire_bytes", 0.0)),
        }
        self._meter_log[meter["rid"]] = meter
        while len(self._meter_log) > 4096:
            self._meter_log.popitem(last=False)
        self._meter_fresh.append(meter)
        self._metered_total += 1

    def meter(self, rid: int) -> dict | None:
        return self._meter_log.get(int(rid))

    def drain_meters(self, limit: int = 64) -> list[dict]:
        out: list[dict] = []
        while self._meter_fresh and len(out) < limit:
            out.append(self._meter_fresh.popleft())
        return out

    def _release(self, req: dict) -> None:
        # close the KV block-seconds rectangle while the blocks are
        # still attributable to this request
        if req.get("kv_anchor") is not None:
            req["kv_block_s"] += req.get("kv_blocks", 0) * max(
                time.perf_counter() - req["kv_anchor"], 0.0
            )
            req["kv_anchor"] = None
        slot = req.get("slot")
        if slot is not None and self._slot_rid[slot] == req["rid"]:
            self._slot_rid[slot] = None
            # stage-0 blocks free now; downstream stages recycle a
            # slot's blocks at its next admission (their pools are
            # sized for all slots fully resident, so lazy reclamation
            # cannot strand capacity)
            try:
                self.engine.release_slot(slot)
            except Exception:  # noqa: BLE001 — teardown must not mask
                pass
        req["slot"] = None
        self._wake.set()

    def _fail_all(self, err: Exception) -> None:
        for rid in list(self._queue):
            req = self._requests.get(rid)
            if req is not None and not req["done"].is_set():
                self._fail(req, err)
        self._queue.clear()
        for req in self._active():
            if not req["done"].is_set():
                self._fail(req, err)

    # ------------------------------------------------------------- stats
    def stats(self) -> dict:
        return {
            "pipeline": {
                "sid": self.sid,
                "stage": 0,
                "n_stages": self.n_stages,
                "ticks": self._ticks,
                "act_wire_bytes": self._act_bytes,
                "failovers": self._failovers,
                "reprefills": self._refills,
                "queued": len(self._queue),
                "active": len(self._active()),
            },
            "metering": {
                "enabled": self.metering,
                "metered_total": self._metered_total,
                "undrained": len(self._meter_fresh),
            },
            "stage0": self.engine.stats(),
            "pool": self.engine.pool.stats(),
        }
