"""1F1B pipeline schedule: SPMD shard_map + ppermute, bounded activation
memory.

The GPipe schedule (parallel/pp.py) is autodiff-transposed: all M forward
micro-batches run, then all M backwards — every stage holds M micro
activations live (or recomputes under remat). 1F1B interleaves: after a
warmup of (S - s) forwards, stage s alternates one-backward/one-forward,
so at most S - s activations are ever in flight (SURVEY §7.3 item 3,
§2.3 PP row; the reference has no schedule at all — thread timing plus a
0.5 s stagger, src/ml/distributed.py:88-112).

Because the backward of micro i starts while micro i+1 is still going
forward, the whole fwd+bwd interleave must be ONE loop — jax.grad cannot
express it. The schedule is therefore hand-scheduled: a static
(slot x stage) action table drives a lax.scan where each slot every stage
executes at most one block compute — a forward, or a backward as a local
jax.vjp (recompute-from-stashed-input, the same cost model as
remat-GPipe) — then hands activations right / cotangents left with one
ppermute pair per slot over ICI.

The last stage folds head+loss into its backward vjp (cotangent of a
scalar is 1.0), which is what lets backwards start immediately — and is
also where tied weights (GPT-2's lm-head = wte) get their head-side
gradient contribution, returned in ``aux`` grads.

Slot count: 2M + 2(S-1) one-compute slots vs GPipe's 2(M + S - 1):
identical bubble fraction (S-1)/(M+S-1) in time, S/M-th the activation
memory.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P

IDLE, FWD, BWD = 0, 1, 2


def simulate_1f1b(num_stages: int, num_micro: int):
    """Greedy lockstep simulation of the 1F1B schedule.

    Returns (act [T, S] in {IDLE, FWD, BWD}, mic [T, S] micro index).
    One compute per stage per slot; transfers land at the end of the
    producing slot, so a consumer can run no earlier than the next slot.
    """
    S, M = num_stages, num_micro
    nf, nb = [0] * S, [0] * S
    fdone = [[None] * M for _ in range(S)]
    bdone = [[None] * M for _ in range(S)]
    act_rows, mic_rows = [], []
    t = 0
    while any(nb[s] < M for s in range(S)):
        acts, mics = [], []
        for s in range(S):
            a, m = IDLE, 0
            can_f = nf[s] < M and (
                s == 0 or (fdone[s - 1][nf[s]] is not None and fdone[s - 1][nf[s]] < t)
            )
            can_b = (
                nb[s] < M
                and nb[s] < nf[s]
                and (
                    s == S - 1
                    or (bdone[s + 1][nb[s]] is not None and bdone[s + 1][nb[s]] < t)
                )
            )
            inflight = nf[s] - nb[s]
            cap = S - s  # 1F1B in-flight bound for stage s
            if can_b and (inflight >= cap or nf[s] == M):
                a, m = BWD, nb[s]
            elif can_f and inflight < cap:
                a, m = FWD, nf[s]
            elif can_b:
                a, m = BWD, nb[s]
            # no forward-past-the-cap fallback: exceeding S - s in-flight
            # would break the 1F1B memory bound, and idling cannot
            # deadlock (backward availability depends only on activations
            # already sent downstream)
            acts.append(a)
            mics.append(m)
        for s in range(S):
            if acts[s] == FWD:
                fdone[s][mics[s]] = t
                nf[s] += 1
            elif acts[s] == BWD:
                bdone[s][mics[s]] = t
                nb[s] += 1
        act_rows.append(acts)
        mic_rows.append(mics)
        t += 1
        if t > 4 * (M + S) + 8:
            raise RuntimeError(f"1F1B schedule deadlock at S={S} M={M}")
    return np.asarray(act_rows, np.int32), np.asarray(mic_rows, np.int32)


def max_inflight(act: np.ndarray, mic: np.ndarray, stage: int = 0) -> int:
    """Peak number of stashed activations at ``stage`` (memory bound)."""
    infl = peak = 0
    for t in range(act.shape[0]):
        if act[t, stage] == FWD:
            infl += 1
            peak = max(peak, infl)
        elif act[t, stage] == BWD:
            infl -= 1
    return peak


@dataclasses.dataclass(eq=False)  # identity hash: instances are jit-stable
class Pipeline1F1B:
    """1F1B over the mesh's ``pipe`` axis, producing gradients directly.

    block_fn(layer_params, x) applies ONE layer; layers_per_stage of them
    per stage from the stacked [S, Lps, ...] params.

    head_loss(aux_params, y, micro_batch, rng) -> scalar loss for one
    micro-batch; ``aux_params`` (head + anything tied, e.g. embeddings)
    is replicated across the pipe axis and its gradient psum'd.

    Loss-reduction restriction: the total is the UNWEIGHTED mean of the
    per-micro losses, which equals the full-batch loss only when
    head_loss is a per-example mean over equal-sized micro-batches. A
    loss normalized by a per-BATCH quantity (e.g. non-pad token count
    across the whole batch) will silently differ from the GPipe path —
    normalize per example (or per micro) instead.
    """

    mesh: Mesh
    block_fn: Callable[[Any, jax.Array], jax.Array]
    num_stages: int
    layers_per_stage: int
    head_loss: Callable[[Any, jax.Array, Any], jax.Array]
    axis: str = "pipe"
    # MoE router aux loss: each stage's aux contribution is LOCAL to its
    # per-micro vjp — the aux output simply gets cotangent aux_weight, so
    # the hand-scheduled interleave needs no extra channel at all
    block_fn_aux: Callable[..., Any] | None = None
    aux_weight: float = 0.0
    # when set, the shard_map additionally binds this axis and shards the
    # activations' token dim (xs dim 2) over it — mirrors Pipeline.seq_axis
    # so ring/ulysses attention compose with 1F1B (VERDICT r3 weak #4).
    # head_loss then runs on each token shard and is pmean'd over the
    # axis, so it must be a UNIFORM per-token mean (see train_grads).
    seq_axis: str | None = None

    def _stage_apply(self, stage_params, x, rng=None, layer0=0, extras=None):
        # shared with the GPipe Pipeline so the (micro, global-layer) rng
        # folding — and thus dropout-mask schedule-independence and the
        # backward's mask recompute — cannot silently diverge
        from tensorlink_tpu.parallel.pp import stage_apply

        return stage_apply(
            self.block_fn, self.layers_per_stage, stage_params, x, rng,
            layer0, extras,
        )

    def _stage_apply_aux(self, stage_params, x, rng=None, layer0=0, extras=None):
        from tensorlink_tpu.parallel.pp import stage_apply_aux

        return stage_apply_aux(
            self.block_fn_aux, self.layers_per_stage, stage_params, x, rng,
            layer0, extras,
        )

    @property
    def _use_aux(self) -> bool:
        return self.block_fn_aux is not None and bool(self.aux_weight)

    # -- per-device program --------------------------------------------
    def _shmap_fn(self, stacked_params, aux_params, xs, micro_batches, rng,
                  extras=None):
        """stacked_params leaves [1, Lps, ...] (this stage); aux_params
        replicated; xs [M, mb, ...] (token dim sharded when seq_axis is
        bound); micro_batches leaves [M, ...] (rank>=3 leaves token-
        sharded under seq); extras (leaves [M, ...], e.g. a replicated
        global attention mask) fully replicated."""
        S = self.num_stages
        axis = self.axis
        idx = jax.lax.axis_index(axis)
        sp = jax.tree.map(lambda a: a[0], stacked_params)
        M = xs.shape[0]
        K = S + 1  # ring-buffer capacity > max in-flight (= S at stage 0)
        layer0 = idx * self.layers_per_stage
        seq = self.seq_axis
        # the branch-free uniform body is only needed when seq collectives
        # actually span devices; at static size 1 every seq replica group
        # is a single device, so the cheaper switch path stays safe (ring
        # models bind the axis even at seq=1 just for axis_index scope)
        seq_spans = seq is not None and self.mesh.shape[seq] > 1
        if rng is not None and seq is not None:
            # decorrelate dropout across token shards — same fold as the
            # GPipe Pipeline so masks stay schedule-independent
            rng = jax.random.fold_in(rng, jax.lax.axis_index(seq))

        def micro_rng(mic_i):
            return None if rng is None else jax.random.fold_in(rng, mic_i)

        def micro_extras(mic_i):
            return (
                None if extras is None
                else jax.tree.map(lambda a: a[mic_i], extras)
            )

        # IMPORTANT: no seq collectives inside the per-micro vjps. Each
        # token shard seeds its LOCAL loss with cotangent 1.0; since the
        # global loss is the pmean of the local ones, every emitted
        # gradient is the gradient of sum_s local_s = seq_size * loss —
        # the final reductions divide by seq_size exactly once. (A pmean
        # inside the vjp'd scalar double-counts: all shards seed the
        # replicated output, so the transpose hands each shard the FULL
        # psum'd cotangent — measured seq_size x overcount.)
        seq_size = 1 if seq is None else jax.lax.axis_size(seq)

        def seq_mean(x):
            # global scalar from per-token-shard partials: uniform mean
            # over equal shards == full-sequence mean (the head_loss
            # contract train_grads documents). Reporting only — never
            # inside a vjp.
            return x if seq is None else jax.lax.pmean(x, seq)

        def head_rng(mic_i):
            # distinct stream from the block folds (mic-first there,
            # sentinel-first here) so head dropout masks are uncorrelated
            # across micro-batches (review finding)
            if rng is None:
                return None
            return jax.random.fold_in(jax.random.fold_in(rng, 0x1F1B), mic_i)

        act_np, mic_np = simulate_1f1b(S, M)
        act_tbl = jnp.asarray(act_np)  # [T, S]
        mic_tbl = jnp.asarray(mic_np)
        T = act_np.shape[0]

        zero_x = jnp.zeros_like(xs[0])
        buf = jnp.zeros((K,) + xs.shape[1:], xs.dtype)
        carry = dict(
            inq=buf,  # activations awaiting forward (keyed micro % K)
            stash=buf,  # forwarded inputs awaiting backward
            cotq=buf,  # cotangents awaiting backward
            send_f=zero_x,  # produced this slot, permuted at slot end
            send_b=zero_x,
            gsp=jax.tree.map(jnp.zeros_like, sp),
            gaux=jax.tree.map(jnp.zeros_like, aux_params),
            dxs=jnp.zeros_like(xs),
            loss=jnp.zeros((), jnp.float32),
        )

        perm_f = [(i, i + 1) for i in range(S - 1)]
        perm_b = [(i + 1, i) for i in range(S - 1)]

        def fwd_op(c, mic_i):
            x = jnp.where(idx == 0, xs[mic_i], c["inq"][mic_i % K])
            y = self._stage_apply(
                sp, x, micro_rng(mic_i), layer0, micro_extras(mic_i)
            )
            c = dict(c)
            c["stash"] = jax.lax.dynamic_update_index_in_dim(
                c["stash"], x, mic_i % K, 0
            )
            c["send_f"] = y
            return c

        def bwd_op(c, mic_i):
            x = c["stash"][mic_i % K]
            gy = c["cotq"][mic_i % K]
            mb = jax.tree.map(lambda a: a[mic_i], micro_batches)

            def last_fn():
                # head+loss folded into the last stage's vjp: the
                # cotangent of a scalar loss is 1.0, so backward can start
                # the moment this micro's forward lands — the property
                # that makes 1F1B possible at all. With MoE aux, the
                # stage's router loss folds into the same scalar.
                def fx(sp_, aux_, x_):
                    ex = micro_extras(mic_i)
                    if self._use_aux:
                        y, a = self._stage_apply_aux(
                            sp_, x_, micro_rng(mic_i), layer0, ex
                        )
                        extra = jnp.float32(self.aux_weight) * a.astype(
                            jnp.float32
                        )
                    else:
                        y = self._stage_apply(
                            sp_, x_, micro_rng(mic_i), layer0, ex
                        )
                        extra = jnp.zeros((), jnp.float32)
                    # LOCAL loss (see seq_size note above): the seq mean
                    # happens once, in the final reductions
                    return self.head_loss(
                        aux_, y, mb, head_rng(mic_i)
                    ).astype(jnp.float32) + extra

                loss, vjp = jax.vjp(fx, sp, aux_params, x)
                gsp_i, gaux_i, gx = vjp(jnp.ones((), jnp.float32))
                return loss, gsp_i, gaux_i, gx

            def mid_fn():
                if self._use_aux:
                    # vjp through (y, LOCAL aux) with cotangents
                    # (gy, aux_weight): the router-loss gradient of THIS
                    # stage's layers rides the same local recompute, no
                    # cross-stage traffic. The seq normalization happens
                    # once in the final reductions (seq_size note above).
                    def fa(sp_, x_):
                        y_, a_ = self._stage_apply_aux(
                            sp_, x_, micro_rng(mic_i), layer0,
                            micro_extras(mic_i),
                        )
                        return y_, a_.astype(jnp.float32)

                    (y, a), vjp = jax.vjp(fa, sp, x)
                    gsp_i, gx = vjp(
                        (gy, jnp.asarray(self.aux_weight, jnp.float32))
                    )
                    loss_i = jnp.float32(self.aux_weight) * a
                else:
                    y, vjp = jax.vjp(
                        lambda sp_, x_: self._stage_apply(
                            sp_, x_, micro_rng(mic_i), layer0,
                            micro_extras(mic_i),
                        ),
                        sp,
                        x,
                    )
                    gsp_i, gx = vjp(gy)
                    loss_i = jnp.zeros((), jnp.float32)
                return (
                    loss_i,
                    gsp_i,
                    jax.tree.map(jnp.zeros_like, aux_params),
                    gx,
                )

            loss_i, gsp_i, gaux_i, gx = jax.lax.cond(idx == S - 1, last_fn, mid_fn)
            c = dict(c)
            c["gsp"] = jax.tree.map(jnp.add, c["gsp"], gsp_i)
            c["gaux"] = jax.tree.map(jnp.add, c["gaux"], gaux_i)
            c["loss"] = c["loss"] + loss_i
            c["send_b"] = gx
            c["dxs"] = jnp.where(
                idx == 0,
                jax.lax.dynamic_update_index_in_dim(c["dxs"], gx, mic_i, 0),
                c["dxs"],
            )
            return c

        def idle_op(c, mic_i):
            return dict(c)

        def uniform_op(c, a, mic_i):
            """Branch-free slot body, used when the seq axis is bound.

            Manual-axis collectives (the ring/ulysses ppermutes and
            all_to_alls inside the blocks) may NOT sit inside lax.switch
            / lax.cond branches selected by another axis's index: seq
            peers always agree on the branch, but XLA compiles one SPMD
            program for ALL devices and pipe rows in different branches
            execute different collective sequences — observed to
            misdeliver on the virtual-CPU mesh and crash outright in a
            minimal repro. So under seq sharding EVERY slot executes one
            vjp with an identical collective structure; the action table
            selects inputs, cotangents, and which results are kept (vjp
            is linear in its cotangents, so zero cotangents make the
            non-taken results exact zeros). Costs one fwd+bwd per slot
            (~1.5x a remat-GPipe step) — the price of composing 1F1B's
            S-s activation bound with sequence sharding; at long context
            memory, not compute, is the binding constraint.
            """
            is_fwd = a == FWD
            is_bwd = a == BWD
            is_last = idx == S - 1
            pos = mic_i % K
            x_fwd = jnp.where(idx == 0, xs[mic_i], c["inq"][pos])
            x = jnp.where(is_bwd, c["stash"][pos], x_fwd)
            gy = c["cotq"][pos]
            mb = jax.tree.map(lambda a_: a_[mic_i], micro_batches)

            def g(sp_, aux_, x_):
                ex = micro_extras(mic_i)
                if self._use_aux:
                    y, av = self._stage_apply_aux(
                        sp_, x_, micro_rng(mic_i), layer0, ex
                    )
                    av = av.astype(jnp.float32)
                else:
                    y = self._stage_apply(
                        sp_, x_, micro_rng(mic_i), layer0, ex
                    )
                    av = jnp.zeros((), jnp.float32)
                # head_loss runs on EVERY stage for structural uniformity
                # but on zeros off the last stage: the select kills its
                # gradient exactly, and garbage mid-stage activations
                # cannot NaN the loss path
                y_head = jnp.where(is_last, y, jnp.zeros_like(y))
                hl = self.head_loss(
                    aux_, y_head, mb, head_rng(mic_i)
                ).astype(jnp.float32)
                return y, hl, av

            (y, hl, av), vjp = jax.vjp(g, sp, aux_params, x)
            # cotangent selection replaces branch selection: mid stages
            # propagate gy into y, the last stage seeds the scalar loss
            # (its cotq holds garbage — nothing ever sends it cotangents)
            take_gy = jnp.logical_and(is_bwd, jnp.logical_not(is_last))
            cot_y = jnp.where(take_gy, gy, jnp.zeros_like(gy)).astype(y.dtype)
            cot_hl = jnp.where(
                jnp.logical_and(is_bwd, is_last), 1.0, 0.0
            ).astype(jnp.float32)
            cot_av = jnp.where(
                is_bwd, jnp.float32(self.aux_weight), 0.0
            )
            gsp_i, gaux_i, gx = vjp((cot_y, cot_hl, cot_av))

            c = dict(c)
            c["stash"] = jax.lax.dynamic_update_index_in_dim(
                c["stash"], jnp.where(is_fwd, x, c["stash"][pos]), pos, 0
            )
            c["send_f"] = jnp.where(is_fwd, y, zero_x)
            c["send_b"] = jnp.where(is_bwd, gx.astype(zero_x.dtype), zero_x)
            # zero cotangents already zeroed gsp_i/gaux_i on non-bwd slots
            c["gsp"] = jax.tree.map(jnp.add, c["gsp"], gsp_i)
            c["gaux"] = jax.tree.map(jnp.add, c["gaux"], gaux_i)
            loss_i = jnp.where(is_last, hl, 0.0)
            if self._use_aux:
                loss_i = loss_i + jnp.float32(self.aux_weight) * av
            c["loss"] = c["loss"] + jnp.where(is_bwd, loss_i, 0.0)
            c["dxs"] = jnp.where(
                jnp.logical_and(idx == 0, is_bwd),
                jax.lax.dynamic_update_index_in_dim(
                    c["dxs"], gx.astype(c["dxs"].dtype), mic_i, 0
                ),
                c["dxs"],
            )
            return c

        def slot(c, t):
            a = act_tbl[t, idx]
            mic_i = mic_tbl[t, idx]
            c = dict(c)
            c["send_f"] = zero_x  # stale sends must not be re-delivered
            c["send_b"] = zero_x
            if not seq_spans:
                c = jax.lax.switch(a, [idle_op, fwd_op, bwd_op], c, mic_i)
            else:
                c = uniform_op(c, a, mic_i)

            if S > 1:
                recv_f = jax.lax.ppermute(c["send_f"], axis, perm_f)
                recv_b = jax.lax.ppermute(c["send_b"], axis, perm_b)
                # left neighbor's slot-t action decides whether recv_f is
                # a real activation, and for which micro
                l_idx = jnp.maximum(idx - 1, 0)
                l_act = act_tbl[t, l_idx]
                l_mic = mic_tbl[t, l_idx]
                take_f = jnp.logical_and(idx > 0, l_act == FWD)
                pos_f = l_mic % K
                new_in = jnp.where(take_f, recv_f, c["inq"][pos_f])
                c["inq"] = jax.lax.dynamic_update_index_in_dim(
                    c["inq"], new_in, pos_f, 0
                )
                r_idx = jnp.minimum(idx + 1, S - 1)
                r_act = act_tbl[t, r_idx]
                r_mic = mic_tbl[t, r_idx]
                take_b = jnp.logical_and(idx < S - 1, r_act == BWD)
                pos_b = r_mic % K
                new_cot = jnp.where(take_b, recv_b, c["cotq"][pos_b])
                c["cotq"] = jax.lax.dynamic_update_index_in_dim(
                    c["cotq"], new_cot, pos_b, 0
                )
            return c, None

        carry, _ = jax.lax.scan(slot, carry, jnp.arange(T))

        # reductions: loss/aux/dxs live on one stage each — psum fills in.
        # Each micro's vjp used cotangent 1.0 on the LOCAL shard loss, so
        # accumulated grads are of the SUM of micro losses summed over
        # token shards; the reported loss is the mean over micros AND
        # shards — scale by 1/M and (once) by 1/seq_size to match.
        inv = (1.0 / M) * (1.0 / seq_size)
        loss = seq_mean(jax.lax.psum(carry["loss"], axis) / M)
        gaux = jax.lax.psum(
            jax.tree.map(lambda g: g * inv, carry["gaux"]),
            axis if seq is None else (axis, seq),
        )
        dxs = jax.lax.psum(
            jnp.where(idx == 0, carry["dxs"] * inv, jnp.zeros_like(carry["dxs"])),
            axis,
        )
        gsp = jax.tree.map(lambda g: g[None] * inv, carry["gsp"])  # [1, Lps, ...]
        if seq is not None:
            gsp = jax.lax.psum(gsp, seq)
        return loss, gsp, gaux, dxs

    # -- public ----------------------------------------------------------
    def train_grads(self, stacked_params, aux_params, xs, micro_batches,
                    rng=None, extras=None):
        """xs: [M, mb, ...] embedded activations; micro_batches: pytree
        with leading [M, ...] leaves; ``rng`` enables dropout in blocks;
        ``extras`` (leaves [M, ...]) are per-micro auxiliary inputs
        handed replicated to every stage (e.g. a global attention mask).
        -> (mean loss, stage grads [S, Lps, ...], aux grads,
        dxs [M, mb, ...]).

        With ``seq_axis`` set, xs (dim 2) and every rank>=3
        micro_batches leaf are token-sharded over the axis and head_loss
        runs per shard, combined by pmean — so head_loss MUST be a
        uniform per-token mean for the result to equal the unsharded
        loss (same contract as the per-micro mean restriction above)."""
        param_specs = jax.tree.map(lambda _: P(self.axis), stacked_params)
        axes = {self.axis}
        xs_spec = P()
        mb_specs = jax.tree.map(lambda _: P(), micro_batches)
        if self.seq_axis is not None:
            axes.add(self.seq_axis)
            xs_spec = P(None, None, self.seq_axis)  # [M, mb, T, ...]
            # token-dim leaves ([M, mb, T, ...]) shard over seq; lower-rank
            # leaves (e.g. per-example labels [M, mb]) stay replicated
            mb_specs = jax.tree.map(
                lambda a: P(None, None, self.seq_axis) if a.ndim >= 3 else P(),
                micro_batches,
            )
        has_rng = rng is not None
        rng_specs = (P(),) if has_rng else ()
        ex_specs = (
            () if extras is None else (jax.tree.map(lambda _: P(), extras),)
        )
        fn = jax.shard_map(
            lambda a, b, c, d, *rest: self._shmap_fn(
                a, b, c, d,
                rest[0] if has_rng else None,
                (rest[1] if has_rng else rest[0]) if extras is not None else None,
            ),
            mesh=self.mesh,
            in_specs=(param_specs, P(), xs_spec, mb_specs) + rng_specs + ex_specs,
            out_specs=(P(), param_specs, P(), xs_spec),
            axis_names=frozenset(axes),
            check_vma=False,
        )
        args = (stacked_params, aux_params, xs, micro_batches)
        if has_rng:
            args += (rng,)
        if extras is not None:
            args += (extras,)
        return fn(*args)

    @property
    def bubble_fraction(self) -> Callable[[int], float]:
        # slots = 2M + 2(S-1); useful = 2M — same fraction as GPipe,
        # with S/M-th the activation memory
        S = self.num_stages
        return lambda m: (S - 1) / (m + S - 1)
