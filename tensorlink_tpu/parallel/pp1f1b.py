"""1F1B pipeline schedule: SPMD shard_map + ppermute, bounded activation
memory.

The GPipe schedule (parallel/pp.py) is autodiff-transposed: all M forward
micro-batches run, then all M backwards — every stage holds M micro
activations live (or recomputes under remat). 1F1B interleaves: after a
warmup of (S - s) forwards, stage s alternates one-backward/one-forward,
so at most S - s activations are ever in flight (SURVEY §7.3 item 3,
§2.3 PP row; the reference has no schedule at all — thread timing plus a
0.5 s stagger, src/ml/distributed.py:88-112).

Because the backward of micro i starts while micro i+1 is still going
forward, the whole fwd+bwd interleave must be ONE loop — jax.grad cannot
express it. The schedule is therefore hand-scheduled: a static
(slot x stage) action table drives a lax.scan where each slot every stage
executes at most one block compute — a forward, or a backward as a local
jax.vjp (recompute-from-stashed-input, the same cost model as
remat-GPipe) — then hands activations right / cotangents left with one
ppermute pair per slot over ICI.

The last stage folds head+loss into its backward vjp (cotangent of a
scalar is 1.0), which is what lets backwards start immediately — and is
also where tied weights (GPT-2's lm-head = wte) get their head-side
gradient contribution, returned in ``aux`` grads.

Slot count: 2M + 2(S-1) one-compute slots vs GPipe's 2(M + S - 1):
identical bubble fraction (S-1)/(M+S-1) in time, S/M-th the activation
memory.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P

IDLE, FWD, BWD = 0, 1, 2


def simulate_1f1b(num_stages: int, num_micro: int):
    """Greedy lockstep simulation of the 1F1B schedule.

    Returns (act [T, S] in {IDLE, FWD, BWD}, mic [T, S] micro index).
    One compute per stage per slot; transfers land at the end of the
    producing slot, so a consumer can run no earlier than the next slot.
    """
    S, M = num_stages, num_micro
    nf, nb = [0] * S, [0] * S
    fdone = [[None] * M for _ in range(S)]
    bdone = [[None] * M for _ in range(S)]
    act_rows, mic_rows = [], []
    t = 0
    while any(nb[s] < M for s in range(S)):
        acts, mics = [], []
        for s in range(S):
            a, m = IDLE, 0
            can_f = nf[s] < M and (
                s == 0 or (fdone[s - 1][nf[s]] is not None and fdone[s - 1][nf[s]] < t)
            )
            can_b = (
                nb[s] < M
                and nb[s] < nf[s]
                and (
                    s == S - 1
                    or (bdone[s + 1][nb[s]] is not None and bdone[s + 1][nb[s]] < t)
                )
            )
            inflight = nf[s] - nb[s]
            cap = S - s  # 1F1B in-flight bound for stage s
            if can_b and (inflight >= cap or nf[s] == M):
                a, m = BWD, nb[s]
            elif can_f and inflight < cap:
                a, m = FWD, nf[s]
            elif can_b:
                a, m = BWD, nb[s]
            # no forward-past-the-cap fallback: exceeding S - s in-flight
            # would break the 1F1B memory bound, and idling cannot
            # deadlock (backward availability depends only on activations
            # already sent downstream)
            acts.append(a)
            mics.append(m)
        for s in range(S):
            if acts[s] == FWD:
                fdone[s][mics[s]] = t
                nf[s] += 1
            elif acts[s] == BWD:
                bdone[s][mics[s]] = t
                nb[s] += 1
        act_rows.append(acts)
        mic_rows.append(mics)
        t += 1
        if t > 4 * (M + S) + 8:
            raise RuntimeError(f"1F1B schedule deadlock at S={S} M={M}")
    return np.asarray(act_rows, np.int32), np.asarray(mic_rows, np.int32)


def max_inflight(act: np.ndarray, mic: np.ndarray, stage: int = 0) -> int:
    """Peak number of stashed activations at ``stage`` (memory bound)."""
    infl = peak = 0
    for t in range(act.shape[0]):
        if act[t, stage] == FWD:
            infl += 1
            peak = max(peak, infl)
        elif act[t, stage] == BWD:
            infl -= 1
    return peak


@dataclasses.dataclass(eq=False)  # identity hash: instances are jit-stable
class Pipeline1F1B:
    """1F1B over the mesh's ``pipe`` axis, producing gradients directly.

    block_fn(layer_params, x) applies ONE layer; layers_per_stage of them
    per stage from the stacked [S, Lps, ...] params.

    head_loss(aux_params, y, micro_batch, rng) -> scalar loss for one
    micro-batch; ``aux_params`` (head + anything tied, e.g. embeddings)
    is replicated across the pipe axis and its gradient psum'd.

    Loss-reduction restriction: the total is the UNWEIGHTED mean of the
    per-micro losses, which equals the full-batch loss only when
    head_loss is a per-example mean over equal-sized micro-batches. A
    loss normalized by a per-BATCH quantity (e.g. non-pad token count
    across the whole batch) will silently differ from the GPipe path —
    normalize per example (or per micro) instead.
    """

    mesh: Mesh
    block_fn: Callable[[Any, jax.Array], jax.Array]
    num_stages: int
    layers_per_stage: int
    head_loss: Callable[[Any, jax.Array, Any], jax.Array]
    axis: str = "pipe"
    # MoE router aux loss: each stage's aux contribution is LOCAL to its
    # per-micro vjp — the aux output simply gets cotangent aux_weight, so
    # the hand-scheduled interleave needs no extra channel at all
    block_fn_aux: Callable[..., Any] | None = None
    aux_weight: float = 0.0

    def _stage_apply(self, stage_params, x, rng=None, layer0=0):
        # shared with the GPipe Pipeline so the (micro, global-layer) rng
        # folding — and thus dropout-mask schedule-independence and the
        # backward's mask recompute — cannot silently diverge
        from tensorlink_tpu.parallel.pp import stage_apply

        return stage_apply(
            self.block_fn, self.layers_per_stage, stage_params, x, rng, layer0
        )

    def _stage_apply_aux(self, stage_params, x, rng=None, layer0=0):
        from tensorlink_tpu.parallel.pp import stage_apply_aux

        return stage_apply_aux(
            self.block_fn_aux, self.layers_per_stage, stage_params, x, rng,
            layer0,
        )

    @property
    def _use_aux(self) -> bool:
        return self.block_fn_aux is not None and bool(self.aux_weight)

    # -- per-device program --------------------------------------------
    def _shmap_fn(self, stacked_params, aux_params, xs, micro_batches, rng):
        """stacked_params leaves [1, Lps, ...] (this stage); aux_params,
        xs [M, mb, ...], micro_batches (leaves [M, ...]) replicated."""
        S = self.num_stages
        axis = self.axis
        idx = jax.lax.axis_index(axis)
        sp = jax.tree.map(lambda a: a[0], stacked_params)
        M = xs.shape[0]
        K = S + 1  # ring-buffer capacity > max in-flight (= S at stage 0)
        layer0 = idx * self.layers_per_stage

        def micro_rng(mic_i):
            return None if rng is None else jax.random.fold_in(rng, mic_i)

        def head_rng(mic_i):
            # distinct stream from the block folds (mic-first there,
            # sentinel-first here) so head dropout masks are uncorrelated
            # across micro-batches (review finding)
            if rng is None:
                return None
            return jax.random.fold_in(jax.random.fold_in(rng, 0x1F1B), mic_i)

        act_np, mic_np = simulate_1f1b(S, M)
        act_tbl = jnp.asarray(act_np)  # [T, S]
        mic_tbl = jnp.asarray(mic_np)
        T = act_np.shape[0]

        zero_x = jnp.zeros_like(xs[0])
        buf = jnp.zeros((K,) + xs.shape[1:], xs.dtype)
        carry = dict(
            inq=buf,  # activations awaiting forward (keyed micro % K)
            stash=buf,  # forwarded inputs awaiting backward
            cotq=buf,  # cotangents awaiting backward
            send_f=zero_x,  # produced this slot, permuted at slot end
            send_b=zero_x,
            gsp=jax.tree.map(jnp.zeros_like, sp),
            gaux=jax.tree.map(jnp.zeros_like, aux_params),
            dxs=jnp.zeros_like(xs),
            loss=jnp.zeros((), jnp.float32),
        )

        perm_f = [(i, i + 1) for i in range(S - 1)]
        perm_b = [(i + 1, i) for i in range(S - 1)]

        def fwd_op(c, mic_i):
            x = jnp.where(idx == 0, xs[mic_i], c["inq"][mic_i % K])
            y = self._stage_apply(sp, x, micro_rng(mic_i), layer0)
            c = dict(c)
            c["stash"] = jax.lax.dynamic_update_index_in_dim(
                c["stash"], x, mic_i % K, 0
            )
            c["send_f"] = y
            return c

        def bwd_op(c, mic_i):
            x = c["stash"][mic_i % K]
            gy = c["cotq"][mic_i % K]
            mb = jax.tree.map(lambda a: a[mic_i], micro_batches)

            def last_fn():
                # head+loss folded into the last stage's vjp: the
                # cotangent of a scalar loss is 1.0, so backward can start
                # the moment this micro's forward lands — the property
                # that makes 1F1B possible at all. With MoE aux, the
                # stage's router loss folds into the same scalar.
                def fx(sp_, aux_, x_):
                    if self._use_aux:
                        y, a = self._stage_apply_aux(
                            sp_, x_, micro_rng(mic_i), layer0
                        )
                        extra = jnp.float32(self.aux_weight) * a.astype(
                            jnp.float32
                        )
                    else:
                        y = self._stage_apply(sp_, x_, micro_rng(mic_i), layer0)
                        extra = jnp.zeros((), jnp.float32)
                    return self.head_loss(
                        aux_, y, mb, head_rng(mic_i)
                    ).astype(jnp.float32) + extra

                loss, vjp = jax.vjp(fx, sp, aux_params, x)
                gsp_i, gaux_i, gx = vjp(jnp.ones((), jnp.float32))
                return loss, gsp_i, gaux_i, gx

            def mid_fn():
                if self._use_aux:
                    # vjp through (y, aux) with cotangents (gy, aux_weight):
                    # the router-loss gradient of THIS stage's layers rides
                    # the same local recompute, no cross-stage traffic
                    (y, a), vjp = jax.vjp(
                        lambda sp_, x_: self._stage_apply_aux(
                            sp_, x_, micro_rng(mic_i), layer0
                        ),
                        sp,
                        x,
                    )
                    gsp_i, gx = vjp(
                        (gy, jnp.asarray(self.aux_weight, a.dtype))
                    )
                    loss_i = (
                        jnp.float32(self.aux_weight) * a.astype(jnp.float32)
                    )
                else:
                    y, vjp = jax.vjp(
                        lambda sp_, x_: self._stage_apply(
                            sp_, x_, micro_rng(mic_i), layer0
                        ),
                        sp,
                        x,
                    )
                    gsp_i, gx = vjp(gy)
                    loss_i = jnp.zeros((), jnp.float32)
                return (
                    loss_i,
                    gsp_i,
                    jax.tree.map(jnp.zeros_like, aux_params),
                    gx,
                )

            loss_i, gsp_i, gaux_i, gx = jax.lax.cond(idx == S - 1, last_fn, mid_fn)
            c = dict(c)
            c["gsp"] = jax.tree.map(jnp.add, c["gsp"], gsp_i)
            c["gaux"] = jax.tree.map(jnp.add, c["gaux"], gaux_i)
            c["loss"] = c["loss"] + loss_i
            c["send_b"] = gx
            c["dxs"] = jnp.where(
                idx == 0,
                jax.lax.dynamic_update_index_in_dim(c["dxs"], gx, mic_i, 0),
                c["dxs"],
            )
            return c

        def idle_op(c, mic_i):
            return dict(c)

        def slot(c, t):
            a = act_tbl[t, idx]
            mic_i = mic_tbl[t, idx]
            c = dict(c)
            c["send_f"] = zero_x  # stale sends must not be re-delivered
            c["send_b"] = zero_x
            c = jax.lax.switch(a, [idle_op, fwd_op, bwd_op], c, mic_i)

            if S > 1:
                recv_f = jax.lax.ppermute(c["send_f"], axis, perm_f)
                recv_b = jax.lax.ppermute(c["send_b"], axis, perm_b)
                # left neighbor's slot-t action decides whether recv_f is
                # a real activation, and for which micro
                l_idx = jnp.maximum(idx - 1, 0)
                l_act = act_tbl[t, l_idx]
                l_mic = mic_tbl[t, l_idx]
                take_f = jnp.logical_and(idx > 0, l_act == FWD)
                pos_f = l_mic % K
                new_in = jnp.where(take_f, recv_f, c["inq"][pos_f])
                c["inq"] = jax.lax.dynamic_update_index_in_dim(
                    c["inq"], new_in, pos_f, 0
                )
                r_idx = jnp.minimum(idx + 1, S - 1)
                r_act = act_tbl[t, r_idx]
                r_mic = mic_tbl[t, r_idx]
                take_b = jnp.logical_and(idx < S - 1, r_act == BWD)
                pos_b = r_mic % K
                new_cot = jnp.where(take_b, recv_b, c["cotq"][pos_b])
                c["cotq"] = jax.lax.dynamic_update_index_in_dim(
                    c["cotq"], new_cot, pos_b, 0
                )
            return c, None

        carry, _ = jax.lax.scan(slot, carry, jnp.arange(T))

        # reductions: loss/aux/dxs live on one stage each — psum fills in.
        # Each micro's vjp used cotangent 1.0, so accumulated grads are of
        # the SUM of micro losses; the reported loss is the MEAN — scale
        # everything by 1/M to match.
        inv_m = 1.0 / M
        loss = jax.lax.psum(carry["loss"], axis) * inv_m
        gaux = jax.lax.psum(
            jax.tree.map(lambda g: g * inv_m, carry["gaux"]), axis
        )
        dxs = jax.lax.psum(
            jnp.where(idx == 0, carry["dxs"] * inv_m, jnp.zeros_like(carry["dxs"])),
            axis,
        )
        gsp = jax.tree.map(lambda g: g[None] * inv_m, carry["gsp"])  # [1, Lps, ...]
        return loss, gsp, gaux, dxs

    # -- public ----------------------------------------------------------
    def train_grads(self, stacked_params, aux_params, xs, micro_batches, rng=None):
        """xs: [M, mb, ...] embedded activations; micro_batches: pytree
        with leading [M, ...] leaves; ``rng`` enables dropout in blocks.
        -> (mean loss, stage grads [S, Lps, ...], aux grads,
        dxs [M, mb, ...])."""
        param_specs = jax.tree.map(lambda _: P(self.axis), stacked_params)
        extra = () if rng is None else (rng,)
        fn = jax.shard_map(
            lambda a, b, c, d, *r: self._shmap_fn(
                a, b, c, d, r[0] if r else None
            ),
            mesh=self.mesh,
            in_specs=(param_specs, P(), P(), P()) + tuple(P() for _ in extra),
            out_specs=(P(), param_specs, P(), P()),
            axis_names=frozenset({self.axis}),
            check_vma=False,
        )
        return fn(stacked_params, aux_params, xs, micro_batches, *extra)

    @property
    def bubble_fraction(self) -> Callable[[int], float]:
        # slots = 2M + 2(S-1); useful = 2M — same fraction as GPipe,
        # with S/M-th the activation memory
        S = self.num_stages
        return lambda m: (S - 1) / (m + S - 1)
