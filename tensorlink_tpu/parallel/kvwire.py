"""Paged KV blocks as the wire unit (disaggregated prefill/decode).

Disaggregated serving splits one request across the mesh: a PREFILL
worker runs chunked prefill into its local ``BlockPool`` and ships only
the filled block payloads plus their logical metadata; the DECODE
worker grafts the blocks into its own pool through the block-table
indirection and decodes as if it had prefilled locally. This module is
the wire format between the two legs:

- the payload is ``{per-layer k/v block stacks, prompt ids, scalars}``
  where every k/v array is ``[n_blocks, block_size, Hkv, D]`` — BLOCK
  granularity, never a contiguous ``[T]``-width cache (the
  bandwidth-optimal discipline of arXiv 2112.01075: ship exactly the
  logical blocks, reassemble through indirection, no materialized
  intermediate on either side); int8-quantized pools
  (``kv_quant="int8"``) ship their int8 block stacks plus the
  per-(slot, kv-head) f32 scale siblings natively under
  ``KV_WIRE_INT8_SCHEMA`` — roughly half the bf16 wire bytes, and a
  pre-int8 peer rejects the blob on the schema check;
- bytes ride the native CRC-framed gather (``p2p/serialization.py
  pack_arrays`` over ``native/wirecodec.cpp``): one memory pass
  concatenates + checksums, and the receiver rejects a corrupt blob
  with a typed error instead of decoding garbage into its pool;
- scalar metadata (logical length, first sampled token, RNG seed,
  remaining budget, prefix digest) travels as 0-d arrays INSIDE the
  same blob, so the CRC covers the metadata a decode leg trusts, not
  just the tensors.

``serving.PagedContinuousBatchingEngine.prefill_export`` produces the
payload dict; ``import_prefill`` consumes it. ``pack_kv_payload`` /
``unpack_kv_payload`` are the byte codec between them; the blob's
``len()`` is what the ``kv_wire_bytes_total`` counters on both legs
count.
"""

from __future__ import annotations

import numpy as np

from tensorlink_tpu.p2p.serialization import pack_arrays, unpack_arrays

# bump when the payload schema changes: an old decode worker must
# reject a new prefill worker's blob with a typed error, not misread it
KV_WIRE_SCHEMA = 1
# int8-quantized payloads (kv_quant="int8": per-layer scale stacks ride
# beside the block stacks) stamp THIS version instead: a float payload
# stays byte-identical to schema 1 — old peers interop untouched —
# while a quantized blob reaching a pre-int8 build fails the schema
# check instead of grafting int8 bytes as if they were bf16
KV_WIRE_INT8_SCHEMA = 2

_SCALARS = (
    "schema", "n_valid", "tok0", "seed", "remaining", "block_size",
)


def flatten_kv_payload(payload: dict) -> dict[str, np.ndarray]:
    """Payload dict -> flat ``{name: array}`` for the CRC-framed gather.
    Every field — per-layer block stacks, prompt ids, scalars — becomes
    an array so ONE checksum covers the whole payload."""
    quant = payload.get("kv_quant")
    if quant not in (None, "int8"):
        raise ValueError(f"unknown payload kv_quant {quant!r}")
    schema = KV_WIRE_INT8_SCHEMA if quant == "int8" else KV_WIRE_SCHEMA
    flat: dict[str, np.ndarray] = {
        "prompt_ids": np.asarray(payload["prompt_ids"], np.int32),
    }
    for name in _SCALARS:
        if name == "schema":
            flat[name] = np.asarray(schema, np.int64)
        else:
            flat[name] = np.asarray(int(payload[name]), np.int64)
    digest = payload.get("prefix_digest")
    if digest:
        flat["prefix_digest"] = np.frombuffer(digest, np.uint8)
    for i, layer in enumerate(payload["layers"]):
        flat[f"L{i}.k"] = np.asarray(layer["k"])
        flat[f"L{i}.v"] = np.asarray(layer["v"])
        if quant == "int8":
            # the wire pays int8 block bytes + f32 scale siblings —
            # never a dequantized intermediate (the whole point of
            # shipping the quantized form natively)
            flat[f"L{i}.ks"] = np.asarray(layer["k_scale"], np.float32)
            flat[f"L{i}.vs"] = np.asarray(layer["v_scale"], np.float32)
    return flat


def _scalar(v) -> int:
    return int(np.asarray(v).reshape(-1)[0])


def unflatten_kv_payload(flat: dict[str, np.ndarray]) -> dict:
    schema = _scalar(flat["schema"]) if "schema" in flat else -1
    if schema not in (KV_WIRE_SCHEMA, KV_WIRE_INT8_SCHEMA):
        raise ValueError(
            f"kv wire schema {schema} not in "
            f"({KV_WIRE_SCHEMA}, {KV_WIRE_INT8_SCHEMA}) (peer runs an "
            "incompatible build)"
        )
    quant = schema == KV_WIRE_INT8_SCHEMA
    layers = []
    for i in range(len(flat)):
        k = flat.get(f"L{i}.k")
        if k is None:
            break
        layer = {"k": k, "v": flat[f"L{i}.v"]}
        if quant:
            try:
                layer["k_scale"] = flat[f"L{i}.ks"]
                layer["v_scale"] = flat[f"L{i}.vs"]
            except KeyError as e:
                raise ValueError(
                    f"int8 kv wire payload layer {i} missing scales"
                ) from e
        layers.append(layer)
    if not layers:
        raise ValueError("kv wire payload carries no layer blocks")
    out = {
        "prompt_ids": np.asarray(flat["prompt_ids"], np.int32),
        "layers": layers,
    }
    if quant:
        out["kv_quant"] = "int8"
    for name in _SCALARS[1:]:
        out[name] = _scalar(flat[name])
    if "prefix_digest" in flat:
        out["prefix_digest"] = bytes(
            np.asarray(flat["prefix_digest"], np.uint8).tobytes()
        )
    return out


def pack_kv_payload(payload: dict, codec: str = "zstd") -> bytes:
    """Payload -> one CRC-framed blob (native gather + checksum in a
    single memory pass; zstd on top — decode-side KV blocks are
    low-entropy enough that the compression usually pays for itself
    on a DCN hop)."""
    return pack_arrays(flatten_kv_payload(payload), codec=codec)


def unpack_kv_payload(data: bytes) -> dict:
    """Blob -> payload. Raises ``ValueError`` on CRC mismatch (the
    receiver must never graft a corrupt block into its pool) or on a
    schema/shape the importer cannot trust."""
    return unflatten_kv_payload(unpack_arrays(data))
