"""Data parallelism.

The reference *planned* DP ("dp_factor", gradient averaging among workers
holding the same submodule — src/roles/user.py:161, Whitepaper §21) but
never implemented an allreduce. Here DP is the degenerate-easy case of the
mesh design: shard the batch over the ``data`` axis, replicate params, and
XLA's SPMD partitioner inserts the gradient psum over ICI automatically
when jit consumes sharded inputs and produces replicated params.
"""

from __future__ import annotations

from typing import Callable

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def dp_shard_batch(batch, mesh: Mesh):
    """Put batch leaves with leading dim sharded over 'data'."""
    sh = NamedSharding(mesh, P("data"))
    return jax.tree.map(lambda x: jax.device_put(x, sh), batch)


def dp_train_step(train_step: Callable, mesh: Mesh) -> Callable:
    """Wrap a Trainer-style step so state stays replicated and batches are
    consumed data-sharded. The grad allreduce is implicit in the sharding
    propagation (state out-sharding = replicated)."""
    repl = NamedSharding(mesh, P())
    batch_sh = NamedSharding(mesh, P("data"))

    step = jax.jit(
        train_step,
        in_shardings=(repl, batch_sh, repl),
        out_shardings=(repl, repl),
        donate_argnums=(0,),
    )

    def wrapped(state, batch, rng):
        return step(state, batch, rng)

    return wrapped
