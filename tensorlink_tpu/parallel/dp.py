"""Data parallelism.

The reference *planned* DP ("dp_factor", gradient averaging among workers
holding the same submodule — src/roles/user.py:161, Whitepaper §21) but
never implemented an allreduce. Here DP is the degenerate-easy case of the
mesh design: shard the batch over the ``data`` axis, replicate params, and
XLA's SPMD partitioner inserts the gradient psum over ICI automatically
when jit consumes sharded inputs and produces replicated params.
"""

from __future__ import annotations

from typing import Callable

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def dp_shard_batch(batch, mesh: Mesh):
    """Put batch leaves with leading dim sharded over 'data'."""
    sh = NamedSharding(mesh, P("data"))
    return jax.tree.map(lambda x: jax.device_put(x, sh), batch)


def dp_train_step(train_step: Callable, mesh: Mesh) -> Callable:
    """Wrap a Trainer-style step so state stays replicated and batches are
    consumed data-sharded. The grad allreduce is implicit in the sharding
    propagation (state out-sharding = replicated)."""
    repl = NamedSharding(mesh, P())
    batch_sh = NamedSharding(mesh, P("data"))

    step = jax.jit(
        train_step,
        in_shardings=(repl, batch_sh, repl),
        out_shardings=(repl, repl),
        donate_argnums=(0,),
    )

    def wrapped(state, batch, rng):
        return step(state, batch, rng)

    return wrapped


# -- FSDP (ZeRO-3 style fully sharded data parallelism) -----------------
#
# Replicated DP holds a full copy of params + optimizer moments on every
# data shard; FSDP shards them over the ``data`` axis too, and XLA's SPMD
# partitioner inserts the all-gather at each use site and turns the
# gradient psum into a reduce-scatter (the all-gather's transpose). The
# reference has no analogue (its DP was never implemented at all,
# src/roles/user.py:161); this is the standard TPU expression of
# FSDP/ZeRO — pure sharding annotations, zero new collective code.

# leaves smaller than this stay replicated: an all-gather per use of a
# tiny bias/layernorm costs more in collective latency than the bytes
# it saves (threshold ~ one 256x256 f32 tile per shard)
FSDP_MIN_ELEMS = 2**16


def fsdp_spec(spec: P, shape: tuple, data_size: int, *, axis: str = "data",
              min_elems: int = FSDP_MIN_ELEMS) -> P:
    """Add ``axis`` to one un-sharded dim of ``spec``: the LARGEST
    eligible dim (for even shard sizes — on an embedding table that is
    the vocab dim whenever vocab > model dim), with ties going to the
    LAST dim so square weights shard the output-feature dim. Returns
    ``spec`` unchanged when the leaf is too small, every dim is taken,
    or nothing divides ``data_size``."""
    if data_size <= 1:
        return spec
    n = 1
    for d in shape:
        n *= d
    if n < min_elems:
        return spec
    entries = list(spec) + [None] * (len(shape) - len(spec))
    cand = [
        i for i, (e, d) in enumerate(zip(entries, shape))
        if e is None and d % data_size == 0
    ]
    if not cand:
        return spec
    best = max(cand, key=lambda i: (shape[i], i))
    entries[best] = axis
    while entries and entries[-1] is None:
        entries.pop()
    return P(*entries)


def fsdp_spec_tree(spec_tree, params, data_size: int, *, axis: str = "data",
                   min_elems: int = FSDP_MIN_ELEMS):
    """Map fsdp_spec over a (spec tree, param tree) pair."""
    return jax.tree.map(
        lambda s, p: fsdp_spec(
            s, p.shape, data_size, axis=axis, min_elems=min_elems
        ),
        spec_tree,
        params,
        is_leaf=lambda x: isinstance(x, P),
    )


def fsdp_train_step(train_step: Callable, mesh: Mesh, state,
                    min_elems: int = FSDP_MIN_ELEMS):
    """dp_train_step's FSDP sibling for the non-pipeline Trainer path:
    params AND optimizer moments shard over ``data`` (moments share
    their param's shape, so the same shape-driven spec lands on both and
    they stay aligned). Returns (wrapped_step, sharded_state); feed the
    returned state to the first call — it replaces the replicated one."""
    n = mesh.shape["data"]
    state_sh = jax.tree.map(
        lambda x: NamedSharding(
            mesh, fsdp_spec(P(), x.shape, n, min_elems=min_elems)
        ),
        state,
    )
    repl = NamedSharding(mesh, P())
    batch_sh = NamedSharding(mesh, P("data"))

    step = jax.jit(
        train_step,
        in_shardings=(state_sh, batch_sh, repl),
        out_shardings=(state_sh, repl),
        donate_argnums=(0,),
    )
    return step, jax.device_put(state, state_sh)
