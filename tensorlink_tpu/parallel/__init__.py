from tensorlink_tpu.parallel.dp import dp_shard_batch, dp_train_step  # noqa: F401
from tensorlink_tpu.parallel.tp import shard_params, tp_jit  # noqa: F401
from tensorlink_tpu.parallel.pp import (  # noqa: F401
    Pipeline,
    stack_stage_params,
    unstack_stage_params,
)
from tensorlink_tpu.parallel.kvpool import (  # noqa: F401
    BlockPool,
    PoolExhaustedError,
    PrefixIndex,
)
from tensorlink_tpu.parallel.serving import (  # noqa: F401
    ContinuousBatchingEngine,
    DeadlineExceededError,
    OverloadedError,
    PagedContinuousBatchingEngine,
    PoolOverloadedError,
    Priority,
    PromptTooLongError,
    QueueFullError,
    ServingError,
)
from tensorlink_tpu.parallel.speculative import (  # noqa: F401
    SpecConfig,
    SpeculativeDecoder,
)
