"""Pipeline parallelism: SPMD GPipe schedule via shard_map + ppermute.

The reference implements PP as one Python thread per micro-batch pushing
pickled activations over TCP sockets with no schedule at all (ordering
emerges from thread timing + a 0.5s stagger — src/ml/distributed.py:88-112,
survey §2.3). Here the schedule is an explicit lax.scan over
M + S - 1 ticks inside one jit-compiled SPMD program:

- stage parameters are stacked on a leading [S, ...] axis and sharded over
  the mesh's ``pipe`` axis — each device holds exactly its stage;
- each tick every stage computes its block(s) and hands its activation to
  the next stage with a single `lax.ppermute` hop over ICI (the TPU-native
  replacement for the FORWARD socket send, src/p2p/torch_node.py:138);
- the backward pass needs no hand-written BACKWARD messages at all:
  jax autodiff transposes ppermute into the reverse hop, so one jax.grad
  of the pipelined loss runs the reverse schedule (replacing
  src/ml/distributed.py:114-197 + worker.py:295-350);
- the bubble is the closed-form (S-1)/(M+S-1) — reported, not emergent.

Composes with DP/TP: shard_map binds only the ``pipe`` axis; ``data`` and
``model`` axes stay in XLA's automatic partitioning, so batch-sharded
inputs and TP-sharded stage weights pass straight through.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from tensorlink_tpu.runtime.metrics import pipeline_bubble_fraction


def stage_apply(
    block_fn, layers_per_stage: int, stage_params, x, rng=None, layer0=0,
    extras=None,
):
    """Apply one stage's layers_per_stage blocks (static loop).

    ``rng`` is a per-micro-batch key; each layer folds in its GLOBAL
    layer index (layer0 + l) so dropout masks are unique per
    (micro, layer) and bitwise-reproducible across schedules — the GPipe
    Pipeline and Pipeline1F1B share THIS function so the guarantee (and
    1F1B's backward mask-recompute) cannot silently diverge.

    ``extras`` is this micro's auxiliary input pytree (e.g. a replicated
    attention mask); when given, block_fn is called as
    ``block_fn(lp, x, rng, extras)`` — rng may be None in that form.

    Implemented on the aux loop with a zero aux so the two variants
    cannot drift (XLA removes the dead accumulator)."""
    wrapped = lambda lp, xx, *r: (block_fn(lp, xx, *r), 0.0)  # noqa: E731
    return stage_apply_aux(
        wrapped, layers_per_stage, stage_params, x, rng, layer0, extras
    )[0]


def stage_apply_aux(
    block_fn_aux, layers_per_stage: int, stage_params, x, rng=None, layer0=0,
    extras=None,
):
    """stage_apply variant for blocks with an auxiliary loss (MoE router
    load balancing): block_fn_aux(lp, x[, rng[, extras]]) -> (x, aux).
    Returns (x, summed aux across this stage's layers). Same per-(micro,
    global layer) rng folding as stage_apply."""
    aux = jnp.zeros(())
    for l in range(layers_per_stage):
        lp = jax.tree.map(lambda a: a[l], stage_params)
        r = None if rng is None else jax.random.fold_in(rng, layer0 + l)
        if extras is not None:
            x, a = block_fn_aux(lp, x, r, extras)
        elif rng is None:
            x, a = block_fn_aux(lp, x)
        else:
            x, a = block_fn_aux(lp, x, r)
        aux = aux + a
    return x, aux


def stack_stage_params(layer_params: dict, num_stages: int):
    """{"0": p0, ..., "L-1": pL-1} -> leaves [S, L/S, ...].

    Leading axis 0 is the stage (shard over ``pipe``); axis 1 indexes the
    layers within a stage (looped locally).
    """
    L = len(layer_params)
    if L % num_stages:
        raise ValueError(f"{L} layers not divisible by {num_stages} stages")
    per = L // num_stages
    layers = [layer_params[str(i)] for i in range(L)]
    stages = [
        jax.tree.map(lambda *xs: jnp.stack(xs), *layers[s * per : (s + 1) * per])
        for s in range(num_stages)
    ]
    return jax.tree.map(lambda *xs: jnp.stack(xs), *stages)


def unstack_stage_params(stacked, num_stages: int, layers_per_stage: int) -> dict:
    """Inverse of stack_stage_params."""
    out = {}
    for s in range(num_stages):
        for l in range(layers_per_stage):
            out[str(s * layers_per_stage + l)] = jax.tree.map(
                lambda x: x[s, l], stacked
            )
    return out


@dataclasses.dataclass(eq=False)  # identity hash: instances are jit-stable
class Pipeline:
    """GPipe pipeline over the mesh's ``pipe`` axis.

    block_fn(layer_params, x) applies ONE layer; layers_per_stage of them
    are applied per stage from the stacked params.
    """

    mesh: Mesh
    block_fn: Callable[[Any, jax.Array], jax.Array]
    num_stages: int
    layers_per_stage: int
    axis: str = "pipe"
    # blocks with an auxiliary loss (MoE): block_fn_aux(lp, x[, rng]) ->
    # (x, aux). Enables apply_with_aux; plain __call__ ignores it.
    block_fn_aux: Callable[..., Any] | None = None
    # when set, the shard_map additionally binds this axis manually and
    # shards the activations' token dim (xs dim 2) over it — blocks then
    # run on [mb, T/seq, ...] shards and attention must be the ring impl
    # (parallel/sp.py ring_attention_local via attn_impl="ring")
    seq_axis: str | None = None

    @property
    def bubble_fraction(self) -> Callable[[int], float]:
        return lambda m: pipeline_bubble_fraction(self.num_stages, m)

    # -- per-device program --------------------------------------------
    def _stage_apply(self, stage_params, x, rng=None, layer0=0, extras=None):
        return stage_apply(
            self.block_fn, self.layers_per_stage, stage_params, x, rng,
            layer0, extras,
        )

    def _shmap_fn(self, stacked_params, xs, rng, extras, with_aux: bool = False):
        """Runs per pipe-shard. stacked_params leaves [1, Lps, ...];
        xs [M, mb, ...], rng and extras (leaves [M, ...], or None)
        replicated over pipe."""
        S = self.num_stages
        axis = self.axis
        idx = jax.lax.axis_index(axis)
        sp = jax.tree.map(lambda a: a[0], stacked_params)
        M = xs.shape[0]
        state = jnp.zeros_like(xs[0])
        outputs = jnp.zeros_like(xs)
        perm = [(i, i + 1) for i in range(S - 1)]
        layer0 = idx * self.layers_per_stage
        if rng is not None and self.seq_axis is not None:
            # each seq shard holds different token positions: without this
            # fold every shard would draw bitwise-identical dropout masks
            # (review finding: sequence-correlated dropout noise)
            rng = jax.random.fold_in(
                rng, jax.lax.axis_index(self.seq_axis)
            )

        def tick(carry, t):
            state, outputs, aux = carry
            recv = jax.lax.ppermute(state, axis, perm) if S > 1 else state
            feed = jax.lax.dynamic_index_in_dim(
                xs, jnp.clip(t, 0, M - 1), 0, keepdims=False
            )
            inp = jnp.where(idx == 0, feed, recv)
            mic = jnp.clip(t - idx, 0, M - 1)  # micro processed this tick
            r = None if rng is None else jax.random.fold_in(rng, mic)
            ex = (
                None if extras is None
                else jax.tree.map(
                    lambda a: jax.lax.dynamic_index_in_dim(
                        a, mic, 0, keepdims=False
                    ),
                    extras,
                )
            )
            if with_aux:
                out, a = stage_apply_aux(
                    self.block_fn_aux, self.layers_per_stage, sp, inp, r,
                    layer0, ex,
                )
                # warmup/drain ticks compute on garbage or duplicate
                # micros — their aux must not count
                valid = jnp.logical_and(t >= idx, t - idx <= M - 1)
                aux = aux + jnp.where(valid, a, 0.0)
            else:
                out = self._stage_apply(sp, inp, r, layer0, ex)
            out_idx = jnp.clip(t - (S - 1), 0, M - 1)
            upd = jax.lax.dynamic_update_index_in_dim(outputs, out, out_idx, 0)
            write = jnp.logical_and(t >= S - 1, idx == S - 1)
            outputs = jnp.where(write, upd, outputs)
            return (out, outputs, aux), None

        (_, outputs, aux), _ = jax.lax.scan(
            tick, (state, outputs, jnp.zeros(())), jnp.arange(M + S - 1)
        )
        # Only the last stage holds real outputs; broadcast over the pipe
        # axis so every shard returns the same (replicated) value.
        outputs = jax.lax.psum(
            jnp.where(idx == S - 1, outputs, jnp.zeros_like(outputs)), axis
        )
        if not with_aux:
            return outputs
        # every stage contributed M micro-aux terms: sum across stages,
        # average over micros (aux is a per-batch mean-style loss); with a
        # seq axis each shard routed a token slice — average those too
        aux = jax.lax.psum(aux, axis) / M
        if self.seq_axis is not None:
            aux = jax.lax.pmean(aux, self.seq_axis)
        return outputs, aux

    # -- public ----------------------------------------------------------
    def _run(self, stacked_params, xs, rng, extras, with_aux: bool):
        """Shared shard_map builder for __call__ / apply_with_aux — one
        place for specs and axis binding so the two paths cannot drift."""
        param_specs = jax.tree.map(lambda _: P(self.axis), stacked_params)
        has_rng = rng is not None
        axes = {self.axis}
        xs_spec = P()
        if self.seq_axis is not None:
            axes.add(self.seq_axis)
            xs_spec = P(None, None, self.seq_axis)  # [M, mb, T, ...]
        # extras (e.g. attention masks) are replicated over every bound
        # axis — under seq sharding that is exactly what lets a GLOBAL
        # mask reach every token shard
        ex_specs = (
            () if extras is None
            else (jax.tree.map(lambda _: P(), extras),)
        )
        rng_specs = (P(),) if has_rng else ()
        fn = jax.shard_map(
            lambda sp_, x_, *rest: self._shmap_fn(
                sp_, x_,
                rest[0] if has_rng else None,
                (rest[1] if has_rng else rest[0]) if extras is not None else None,
                with_aux=with_aux,
            ),
            mesh=self.mesh,
            in_specs=(param_specs, xs_spec) + rng_specs + ex_specs,
            out_specs=(xs_spec, P()) if with_aux else xs_spec,
            axis_names=frozenset(axes),
            check_vma=False,
        )
        args = (stacked_params, xs)
        if has_rng:
            args += (rng,)
        if extras is not None:
            args += (extras,)
        return fn(*args)

    def __call__(self, stacked_params, xs, rng=None, extras=None):
        """xs: [M, micro_batch, ...] -> outputs [M, micro_batch, ...].

        Differentiable; wrap in jax.jit (+ value_and_grad) at the call
        site. Not jitted here so it can be traced inside larger programs.
        ``rng`` enables dropout inside blocks (block_fn must then accept a
        third rng argument). ``extras`` (leaves [M, ...]) are per-micro
        auxiliary inputs handed to every stage — block_fn must then take a
        fourth argument."""
        return self._run(stacked_params, xs, rng, extras, with_aux=False)

    def apply_with_aux(self, stacked_params, xs, rng=None, extras=None):
        """Like __call__ but also returns the summed auxiliary loss of all
        valid (stage, micro) applications — requires ``block_fn_aux``.
        Differentiable: jax.grad through (outputs, aux) trains the MoE
        router's load-balancing term inside the pipeline schedule."""
        if self.block_fn_aux is None:
            raise ValueError("apply_with_aux requires block_fn_aux")
        return self._run(stacked_params, xs, rng, extras, with_aux=True)


def pipeline_sharding(mesh: Mesh, axis: str = "pipe") -> NamedSharding:
    """Sharding for stacked stage params (leading stage axis)."""
    return NamedSharding(mesh, P(axis))
