"""Paged KV-cache pool: fixed-size blocks, refcounts, prefix sharing.

The continuous-batching engine (parallel/serving.py) historically
reserved one contiguous ``max_len`` cache region per slot, so HBM
scaled with ``slots x worst-case length`` and two requests sharing a
system prompt each paid a full prefill. This module is the host-side
half of the paged alternative (ROADMAP item 1):

- ``BlockPool``: a free-list allocator over ``num_blocks`` fixed-size
  blocks with per-block refcounts. Blocks whose refcount drops to zero
  but that still back a registered prompt prefix park in an LRU
  "reusable" list — they satisfy future prefix hits for free and are
  evicted (oldest first) only when allocation would otherwise fail.
  Exhaustion raises the typed ``PoolExhaustedError`` (backpressure,
  never a shape error) and lands a flight-recorder event.

- ``PrefixIndex``: a refcount-friendly radix-style index over prompt
  prefixes at block granularity. Keys are CHAINED digests — block i's
  key is ``H(key_{i-1} || tokens[i*bs:(i+1)*bs])`` — so a lookup walks
  the prompt block by block exactly like a radix trie walks edges,
  with O(1) state per step and no collision-prone flat hashing of
  arbitrary-length prefixes. Partial tail blocks (a prompt whose length
  is not a block multiple) index under ``(parent key, fill)`` so an
  exact-prefix request can share them too; writing into a shared block
  is what triggers copy-on-write in the engine.

The DEVICE half — ``block_table[pos // bs] * bs + pos % bs`` cache
addressing — lives in nn/attention.py (the paged cache form) and
parallel/serving.py (the paged engine state); this module is pure
host-side bookkeeping and deliberately jax-free.
"""

from __future__ import annotations

import collections
import hashlib
import time

import numpy as np


class PoolExhaustedError(RuntimeError):
    """No free or evictable block can satisfy the allocation — typed
    backpressure for admission control (the pool-level analogue of
    serving.QueueFullError), never a shape error downstream."""


class BlockPool:
    """Fixed-size KV block allocator with refcounts and LRU reuse.

    A block id is an index into the device-side per-layer pools
    ``[num_blocks, block_size, Hkv, D]`` (nn/attention.py paged form).
    The pool itself never touches device memory — it only decides which
    block ids are live, shared, reusable (cached prefix, refcount 0),
    or free.

    States: FREE (never written / fully forgotten) -> LIVE (refcount
    >= 1) -> REUSABLE (refcount 0 but prefix-registered; an LRU hit
    revives it, allocation pressure evicts it via ``evict_hook``) ->
    FREE.
    """

    def __init__(
        self,
        num_blocks: int,
        block_size: int,
        *,
        metrics=None,
        recorder=None,
    ):
        if num_blocks < 1 or block_size < 1:
            raise ValueError(
                f"need num_blocks >= 1 and block_size >= 1, got "
                f"{num_blocks}, {block_size}"
            )
        self.num_blocks = int(num_blocks)
        self.block_size = int(block_size)
        self.metrics = metrics
        self.recorder = recorder
        self._free: collections.deque[int] = collections.deque(
            range(self.num_blocks)
        )
        self._refs = [0] * self.num_blocks
        # refcount-0 blocks still backing a registered prefix, oldest
        # first — the prefix cache's eviction order
        self._reusable: collections.OrderedDict[int, None] = (
            collections.OrderedDict()
        )
        self._cached: set[int] = set()  # prefix-registered block ids
        # SLO class of the request whose prefix a cached block backs
        # (serving.Priority value; jax-free here on purpose — it is
        # just an eviction rank). Under allocation pressure the least
        # protected class evicts first, oldest-first within a class,
        # so BATCH system prompts never push an INTERACTIVE tenant's
        # resident prefix out of the pool.
        self._cached_prio: dict[int, int] = {}
        # owner wires this to PrefixIndex.forget_block so evicting a
        # reusable block also drops its index entries
        self.evict_hook = None
        self.in_use = 0  # blocks with refcount >= 1

    # ------------------------------------------------------------- events
    def _event(self, kind: str, severity: str = "info", **data) -> None:
        if self.recorder is not None:
            try:
                self.recorder.record(kind, severity, **data)
            except Exception:  # noqa: BLE001 — telemetry must not fail allocs
                pass

    # ---------------------------------------------------------------- API
    @property
    def available(self) -> int:
        """Blocks an alloc() could hand out right now (free + evictable)."""
        return len(self._free) + len(self._reusable)

    def refcount(self, bid: int) -> int:
        return self._refs[bid]

    def alloc(self, n: int = 1) -> list[int]:
        """Allocate ``n`` blocks (refcount 1 each). Prefers never-used
        free blocks; under pressure evicts the oldest reusable blocks
        (forgetting their prefix entries). Raises ``PoolExhaustedError``
        when fewer than ``n`` blocks exist in either state."""
        if n < 0:
            raise ValueError(f"alloc of {n} blocks")
        if self.available < n:
            self._event(
                "kvpool.exhausted", "warn",
                requested=n, free=len(self._free),
                reusable=len(self._reusable), in_use=self.in_use,
            )
            if self.metrics is not None:
                self.metrics.incr("kv_pool_exhausted_total")
            raise PoolExhaustedError(
                f"need {n} KV blocks; {len(self._free)} free + "
                f"{len(self._reusable)} evictable of {self.num_blocks} "
                f"({self.in_use} in use)"
            )
        out: list[int] = []
        evicted = 0
        for _ in range(n):
            if self._free:
                bid = self._free.popleft()
            else:
                bid = self._evict_candidate()
                del self._reusable[bid]
                self._forget(bid)
                evicted += 1
            self._refs[bid] = 1
            self.in_use += 1
            out.append(bid)
        self._event(
            "kvpool.alloc", blocks=n, evicted=evicted, in_use=self.in_use
        )
        return out

    def retain(self, bid: int, priority: int | None = None) -> None:
        """Refcount++ (prefix hit / sharer). Revives a reusable block.
        ``priority`` upgrades (never downgrades) the block's cached
        eviction class: a prefix WARMED by BATCH but HIT by INTERACTIVE
        is protecting interactive traffic and must be ranked by its
        most protected consumer, not its first writer."""
        if self._refs[bid] == 0:
            if bid not in self._reusable:
                raise ValueError(
                    f"retain of free block {bid} (never allocated or "
                    "already forgotten) — allocate it instead"
                )
            del self._reusable[bid]
            self.in_use += 1
        self._refs[bid] += 1
        if priority is not None and bid in self._cached:
            self._cached_prio[bid] = min(
                self._cached_prio.get(bid, 2), int(priority)
            )

    def release(self, bid: int) -> None:
        """Refcount--. At zero the block parks reusable if it still
        backs a registered prefix, else returns to the free list.
        A negative refcount is an accounting bug and raises."""
        if self._refs[bid] <= 0:
            raise ValueError(
                f"release of block {bid} with refcount {self._refs[bid]} "
                "(double free)"
            )
        self._refs[bid] -= 1
        if self._refs[bid] == 0:
            self.in_use -= 1
            if bid in self._cached:
                self._reusable[bid] = None  # newest at the end (LRU)
            else:
                self._free.append(bid)
            self._event("kvpool.free", block=bid, in_use=self.in_use)

    def _evict_candidate(self) -> int:
        """Priority-then-LRU eviction: the OLDEST reusable block of the
        LEAST protected priority class. Iteration is oldest-first, so
        the first block seen of the worst class present wins; a pool
        with no priority annotations degenerates to plain LRU."""
        worst = None
        worst_p = -1
        for bid in self._reusable:  # oldest -> newest
            p = self._cached_prio.get(bid, 2)
            if p > worst_p:
                worst, worst_p = bid, p
                if p >= 2:  # least protected class, oldest — done
                    break
        return worst

    def mark_cached(self, bid: int, priority: int = 2) -> None:
        """Flag a block as prefix-registered: at refcount 0 it parks
        reusable (serving future prefix hits) instead of freeing.
        ``priority`` (serving.Priority value; defaults to the least
        protected class) ranks it for pressure eviction — min-merged
        with any existing annotation, so re-registration can upgrade
        but never strip protection."""
        self._cached.add(bid)
        old = self._cached_prio.get(bid)
        self._cached_prio[bid] = (
            int(priority) if old is None else min(old, int(priority))
        )

    def touch(self, bid: int) -> None:
        """LRU bump for a reusable block that served a read-only hit."""
        if bid in self._reusable:
            self._reusable.move_to_end(bid)

    def _forget(self, bid: int) -> None:
        self._cached.discard(bid)
        self._cached_prio.pop(bid, None)
        if self.evict_hook is not None:
            self.evict_hook(bid)
        self._event("kvpool.evict", block=bid)

    def stats(self) -> dict:
        # "fragmentation" for a paged pool: the fraction of the
        # allocatable headroom that is REUSABLE rather than clean-free
        # — an alloc under pressure must evict (and forget prefix
        # entries) for that fraction of its blocks, so high
        # fragmentation means allocation is about to start costing
        # cache hits
        avail = len(self._free) + len(self._reusable)
        return {
            "num_blocks": self.num_blocks,
            "block_size": self.block_size,
            "blocks_in_use": self.in_use,
            "blocks_free": len(self._free),
            "blocks_reusable": len(self._reusable),
            "blocks_cached": len(self._cached),
            "utilization": round(self.in_use / self.num_blocks, 4),
            "fragmentation": round(
                len(self._reusable) / avail, 4
            ) if avail else 0.0,
        }


class PrefixIndex:
    """Radix-style prompt-prefix index at block granularity.

    Chained digests make each full block a trie edge: matching a prompt
    walks ``key_i = H(key_{i-1} || block_tokens)`` until a key misses.
    Partial tails (the last ``fill < block_size`` tokens of a prompt)
    register under their parent key so exact-prefix requests can share
    them; the caller copy-on-writes those before extending them.

    The index stores BLOCK IDS, not contents — the pool's
    ``evict_hook`` must point at :meth:`forget_block` so an evicted
    block's entries vanish with it.
    """

    def __init__(self, block_size: int):
        self.block_size = int(block_size)
        self._full: dict[bytes, int] = {}  # chain digest -> block id
        # parent digest -> {fill: (digest over fill tokens, block id)}
        self._partial: dict[bytes, dict[int, tuple[bytes, int]]] = {}
        self._by_block: dict[int, list[tuple]] = {}  # bid -> entry keys
        # residency metadata (the /kv introspection surface): parent
        # chain link per full entry (child digest -> parent digest, so
        # a leaf walks back to the root), and last-hit wall time per
        # entry key (full: digest; partial: ("p", parent, fill)) —
        # stamped at register and refreshed by every match() walk
        self._parent: dict[bytes, bytes] = {}
        self._last_hit: dict[object, float] = {}

    @staticmethod
    def _digest(parent: bytes, tokens: np.ndarray) -> bytes:
        return hashlib.sha1(
            parent + np.ascontiguousarray(tokens, np.int32).tobytes()
        ).digest()

    def match(
        self, ids: np.ndarray, *, max_tokens: int | None = None
    ) -> tuple[list[int], int, tuple[int, int] | None]:
        """Longest resident prefix of ``ids``.

        Returns ``(full_blocks, matched_tokens, tail)`` where
        ``full_blocks`` are the block ids covering the first
        ``len(full_blocks) * block_size`` tokens and ``tail`` is an
        optional ``(block_id, fill)`` partial-block hit extending the
        match by ``fill`` more tokens. ``matched_tokens`` counts both.
        Never matches past ``max_tokens`` (callers pass ``len(ids) - 1``
        so at least one token remains to prefill — the sampler needs
        its logits). The caller owns refcounts: nothing is retained
        here."""
        ids = np.asarray(ids).reshape(-1)
        bs = self.block_size
        cap = len(ids) if max_tokens is None else min(max_tokens, len(ids))
        blocks: list[int] = []
        key = b""
        n = 0
        hit_t = time.time()
        while n + bs <= cap:
            nxt = self._digest(key, ids[n:n + bs])
            bid = self._full.get(nxt)
            if bid is None:
                break
            blocks.append(bid)
            key = nxt
            self._last_hit[key] = hit_t
            n += bs
        tail = None
        fills = self._partial.get(key)
        if fills:
            for fill in sorted(fills, reverse=True):
                if n + fill > cap:
                    continue
                digest, bid = fills[fill]
                if self._digest(key, ids[n:n + fill]) == digest:
                    tail = (bid, fill)
                    self._last_hit[("p", key, fill)] = hit_t
                    n += fill
                    break
        return blocks, n, tail

    def register(self, ids: np.ndarray, blocks: list[int]) -> list[int]:
        """Index a prefilled prompt: every full block under its chain
        digest, the partial tail (if any) under its parent. Existing
        entries win (first writer keeps the cache slot — duplicates
        would just shadow it). Returns the block ids newly indexed, so
        the caller can ``pool.mark_cached`` them."""
        ids = np.asarray(ids).reshape(-1)
        bs = self.block_size
        newly: list[int] = []
        key = b""
        n = 0
        reg_t = time.time()
        for bid in blocks:
            if n + bs <= len(ids):
                nxt = self._digest(key, ids[n:n + bs])
                if nxt not in self._full:
                    self._full[nxt] = bid
                    self._parent[nxt] = key
                    self._last_hit[nxt] = reg_t
                    self._by_block.setdefault(bid, []).append(("f", nxt))
                    newly.append(bid)
                key = nxt
                n += bs
            else:
                fill = len(ids) - n
                if fill <= 0:
                    break
                fills = self._partial.setdefault(key, {})
                if fill not in fills:
                    fills[fill] = (self._digest(key, ids[n:n + fill]), bid)
                    self._last_hit[("p", key, fill)] = reg_t
                    self._by_block.setdefault(bid, []).append(
                        ("p", key, fill)
                    )
                    newly.append(bid)
                break
        return newly

    def chain_digest(self, ids: np.ndarray) -> bytes:
        """The chained digest over the FULL blocks of ``ids`` — the key
        the last full block indexes under. Exported with a KV-block
        wire payload (parallel/kvwire.py) so the decode leg can verify
        the token ids it was handed actually correspond to the blocks
        before registering them: the digest it recomputes from the ids
        must match, or the payload is internally inconsistent. Both
        sides computing the SAME chain is also what makes remote blocks
        index into the receiver's ``PrefixIndex`` at the same keys a
        local prefill would have produced."""
        ids = np.asarray(ids).reshape(-1)
        bs = self.block_size
        key = b""
        n = 0
        while n + bs <= len(ids):
            key = self._digest(key, ids[n:n + bs])
            n += bs
        return key

    def forget_block(self, bid: int) -> None:
        """Drop every entry pointing at ``bid`` (pool eviction hook)."""
        for entry in self._by_block.pop(bid, []):
            if entry[0] == "f":
                self._full.pop(entry[1], None)
                self._parent.pop(entry[1], None)
                self._last_hit.pop(entry[1], None)
            else:
                fills = self._partial.get(entry[1])
                if fills is not None:
                    fills.pop(entry[2], None)
                    if not fills:
                        del self._partial[entry[1]]
                self._last_hit.pop(("p", entry[1], entry[2]), None)

    def __len__(self) -> int:
        return len(self._full) + sum(
            len(f) for f in self._partial.values()
        )

    # -------------------------------------------------- residency surface
    def chains(self) -> list[dict]:
        """Every MAXIMAL resident prefix chain: a leaf full-block
        digest (no full child) walked back to the root via the parent
        links, plus every partial-tail entry as its own chain record.
        Block ids are listed root-first — exactly the prefix a future
        prompt would map. Caller holds whatever lock guards the index
        (the engine's scheduler lock)."""
        has_child = set(self._parent.values())
        out: list[dict] = []

        def walk(leaf: bytes) -> list[int]:
            bids: list[int] = []
            key = leaf
            while key:
                bid = self._full.get(key)
                if bid is None:
                    # an INTERIOR ancestor was evicted out from under
                    # this chain (forget_block drops one digest, not
                    # its descendants' parent links): report only the
                    # resident suffix — those blocks are unreachable
                    # garbage awaiting LRU eviction, not a mappable
                    # prefix, but they DO occupy pool blocks
                    break
                bids.append(bid)
                key = self._parent.get(key, b"")
            bids.reverse()
            return bids

        for leaf in self._full:
            if leaf in has_child or leaf in self._partial:
                continue  # interior node: a longer chain covers it
            bids = walk(leaf)
            out.append({
                "digest": leaf.hex()[:16],
                "blocks": len(bids),
                "tokens": len(bids) * self.block_size,
                "block_ids": bids,
                "last_hit": self._last_hit.get(leaf),
            })
        for parent, fills in self._partial.items():
            base = walk(parent) if parent else []
            for fill, (_, bid) in fills.items():
                out.append({
                    "digest": (parent.hex()[:16] or "root")
                    + f"+{fill}",
                    "blocks": len(base) + 1,
                    "tokens": len(base) * self.block_size + fill,
                    "tail_fill": fill,
                    "block_ids": base + [bid],
                    "last_hit": self._last_hit.get(("p", parent, fill)),
                })
        return out


def kv_residency(
    pool: BlockPool | None,
    index: PrefixIndex | None,
    now: float | None = None,
    limit: int = 64,
) -> dict:
    """The ``GET /kv`` body: pool occupancy/fragmentation plus the
    resident prefix chains annotated with the pool's view of each
    chain's blocks — refcounts, eviction priority class (min over the
    chain: its most protected consumer), last-hit age. MUST be called
    under the lock that serializes pool/index mutation (the serving
    engine's scheduler lock) — the point is an exact snapshot, not a
    torn one."""
    t = time.time() if now is None else now
    out: dict = {
        "pool": pool.stats() if pool is not None else None,
        "chains": [],
        "total_chains": 0,
        "prefix_entries": len(index) if index is not None else 0,
    }
    if index is None:
        return out
    chains = index.chains()
    out["total_chains"] = len(chains)
    # the hottest prefixes first; bound the body (a node with thousands
    # of resident chains still answers in one small page)
    chains.sort(key=lambda c: c.get("last_hit") or 0.0, reverse=True)
    for c in chains[:limit]:
        rec = dict(c)
        hit = rec.pop("last_hit", None)
        rec["last_hit_age_s"] = (
            round(max(0.0, t - hit), 3) if hit else None
        )
        if pool is not None:
            bids = rec["block_ids"]
            rec["refs"] = sum(pool.refcount(b) for b in bids)
            rec["priority"] = min(
                (pool._cached_prio.get(b, 2) for b in bids), default=2
            )
        out["chains"].append(rec)
    if len(chains) > limit:
        out["truncated"] = len(chains) - limit
    return out


def kv_summary(
    pool: BlockPool | None, index: PrefixIndex | None
) -> dict:
    """Compact scalar form of :func:`kv_residency` — what rides the
    heartbeat delta to the validator's fleet table (the published-
    residency groundwork for prefix-affinity routing). Same locking
    contract as :func:`kv_residency`."""
    out: dict = {}
    if pool is not None:
        st = pool.stats()
        out.update({
            "num_blocks": st["num_blocks"],
            "used": st["blocks_in_use"],
            "free": st["blocks_free"],
            "reusable": st["blocks_reusable"],
            "cached": st["blocks_cached"],
            "occupancy": st["utilization"],
            "fragmentation": st["fragmentation"],
        })
    if index is not None:
        out["prefix_blocks"] = len(index._by_block)
        out["chains"] = sum(
            1 for _ in index.chains()
        )
    return out
