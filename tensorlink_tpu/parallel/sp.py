"""Sequence/context parallelism: ring attention over the ``seq`` mesh axis.

The reference has no sequence-dimension handling at all (survey §5.7: the
only split anywhere is torch.chunk on the batch dim). For long-context
training the sequence is sharded over the ``seq`` axis; each device holds a
[B, T/S, H, D] slice of q,k,v. Attention over the full sequence is computed
by rotating the K/V block around the ring with `lax.ppermute` S times while
accumulating online-softmax statistics — ICI traffic overlaps with the
block attention compute, and peak memory is one K/V block instead of the
full sequence.

Causal masking uses each block's global offset: a k-block strictly ahead of
the local q-block contributes nothing (masked), the diagonal block gets the
triangular mask, and blocks behind are unmasked. Differentiable end-to-end
(ppermute transposes to the reverse rotation).
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

NEG_INF = -1e30


def _block_attn(q, k, v, q_off, k_off, causal, scale, mask=None):
    """One blockwise attention accumulation step.

    q: [B, Tq, H, D]; k,v: [B, Tk, H, D]. ``mask``, when given, is the
    GLOBAL (replicated) [B, 1, 1|Tglobal, Tglobal] boolean mask; the
    k-block's (and, for a square mask, the q-block's) slice is taken at
    the block offsets. Returns the masked logits [B, H, Tq, Tk].
    """
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k).astype(jnp.float32) * scale
    Tq, Tk = q.shape[1], k.shape[1]
    if causal:
        qpos = q_off + jnp.arange(Tq)[:, None]
        kpos = k_off + jnp.arange(Tk)[None, :]
        keep = qpos >= kpos
        s = jnp.where(keep[None, None], s, NEG_INF)
    if mask is not None:
        mblk = jax.lax.dynamic_slice_in_dim(mask, k_off, Tk, axis=3)
        if mask.shape[2] != 1:  # square mask: also slice the q dim
            mblk = jax.lax.dynamic_slice_in_dim(mblk, q_off, Tq, axis=2)
        s = jnp.where(mblk, s, NEG_INF)
    return s


def ring_attention_local(
    q: jax.Array,  # [B, Tq_local, H, D]
    k: jax.Array,  # [B, Tk_local, H, D]
    v: jax.Array,
    *,
    axis: str = "seq",
    causal: bool = False,
    mask: jax.Array | None = None,  # GLOBAL replicated [B,1,1|T,T] bool
) -> jax.Array:
    """Call INSIDE shard_map over ``axis``. Full-sequence attention for the
    local q shard, K/V rotating around the ring. ``mask`` must be the
    full-sequence mask replicated across the axis (head dim 1); each
    rotation slices the k-block's columns at its global offset, so padded
    workloads can sequence-shard (VERDICT r3 weak #6)."""
    S = jax.lax.axis_size(axis)
    idx = jax.lax.axis_index(axis)
    B, Tq, H, D = q.shape
    Tk = k.shape[1]
    if mask is not None:
        if mask.shape[1] != 1:
            raise NotImplementedError(
                "ring attention supports masks with head dim 1 only"
            )
        if mask.shape[3] != S * Tk:
            raise ValueError(
                f"ring mask must be GLOBAL: last dim {mask.shape[3]} != "
                f"axis_size*Tk_local = {S * Tk} (a token-sharded mask "
                "cannot follow the rotating k-blocks)"
            )
    scale = D ** -0.5
    q_off = idx * Tq

    m = jnp.full((B, H, Tq, 1), NEG_INF, jnp.float32)
    l = jnp.zeros((B, H, Tq, 1), jnp.float32)
    acc = jnp.zeros((B, Tq, H, D), jnp.float32)
    # ring: receive from the next rank, so after r rotations we hold shard
    # (idx + r) % S
    perm = [(i, (i - 1) % S) for i in range(S)]

    def accumulate(carry, k_blk, v_blk, r):
        m, l, acc = carry
        k_off = ((idx + r) % S) * Tk
        s = _block_attn(q, k_blk, v_blk, q_off, k_off, causal, scale, mask)
        m_cur = jnp.max(s, axis=-1, keepdims=True)
        m_new = jnp.maximum(m, m_cur)
        p = jnp.exp(s - m_new)
        if causal or mask is not None:
            p = jnp.where(s <= NEG_INF / 2, 0.0, p)
        alpha = jnp.exp(m - m_new)
        l = alpha * l + jnp.sum(p, axis=-1, keepdims=True)
        pv = jnp.einsum("bhqk,bkhd->bqhd", p.astype(v_blk.dtype), v_blk).astype(
            jnp.float32
        )
        acc = acc * alpha.transpose(0, 2, 1, 3) + pv
        return (m_new, l, acc)

    # local block first (no collective), then S-1 rotate-and-accumulate
    # steps — exactly S-1 ppermute pairs, none wasted.
    carry = accumulate((m, l, acc), k, v, 0)

    def step(carry_kv, r):
        carry, k_blk, v_blk = carry_kv
        k_blk = jax.lax.ppermute(k_blk, axis, perm)
        v_blk = jax.lax.ppermute(v_blk, axis, perm)
        carry = accumulate(carry, k_blk, v_blk, r)
        return (carry, k_blk, v_blk), None

    if S > 1:
        (carry, _, _), _ = jax.lax.scan(
            step, (carry, k, v), jnp.arange(1, S)
        )
    m, l, acc = carry
    l_safe = jnp.where(l == 0.0, 1.0, l)
    out = acc / l_safe.transpose(0, 2, 1, 3)
    return out.astype(q.dtype)


# ------------------------------------------------------ ring + Pallas flash
# The einsum ring above materializes each [B, H, Tq, Tk] block's score
# matrix in registers/HBM per rotation. At the long sequences SP exists
# for, the Pallas flash kernels (ops/pallas/flash_attention.py) do the
# same block math streaming through VMEM — so the ring's local compute
# should BE the kernel (VERDICT r4 weak #5). Design: per rotation the
# kernel emits a NORMALIZED block output plus its per-row LSE
# (flash_attention_fwd_lse); blocks merge by logsumexp reweighting, which
# is algebraically the same online softmax the einsum ring carries.
# Backward re-rotates K/V and calls the blockwise dq/dk/dv kernels with
# the FINAL lse (p = exp(s - lse_final) makes per-block contributions
# exact partial sums); dk/dv accumulators travel with their blocks and
# arrive home after S hops. Causal block types (behind/diagonal/ahead)
# depend on the traced (axis_index, rotation) pair, so the three kernel
# variants sit in a lax.switch. GQA rotates the NARROW [B, Tk, Hkv, D]
# K/V (the kernels read groups via index maps) — Hkv/H-th the ICI bytes
# of the einsum ring's pre-repeat.


def _rf_block_fwd(qt, k_blk, v_blk, kvm, k_idx, idx, causal, bq, bk,
                  interpret):
    """One rotation's kernel call -> (o [B,H,Tq,D] f32, lse [B,H,Tq] f32
    with fully-masked rows at -inf). qt is [B,H,Tq,D]; k_blk/v_blk are the
    narrow [B,Tk,Hkv,D] rotating shards."""
    from tensorlink_tpu.ops.pallas.flash_attention import (
        LSE_MASKED, flash_attention_fwd_lse,
    )

    kt, vt = k_blk.swapaxes(1, 2), v_blk.swapaxes(1, 2)
    args = (qt, kt, vt) if kvm is None else (qt, kt, vt, kvm)

    def call(is_causal):
        def f(qt_, kt_, vt_, *m):
            o, lse = flash_attention_fwd_lse(
                qt_, kt_, vt_, m[0] if m else None, causal=is_causal,
                block_q=bq, block_k=bk, interpret=interpret,
            )
            lse = jnp.where(lse >= LSE_MASKED / 2, -jnp.inf, lse)
            return o.astype(jnp.float32), lse

        return f

    if not causal:
        return call(False)(*args)

    def ahead(qt_, kt_, vt_, *m):
        B, H, Tq, D = qt_.shape
        return (
            jnp.zeros((B, H, Tq, D), jnp.float32),
            jnp.full((B, H, Tq), -jnp.inf, jnp.float32),
        )

    branch = jnp.where(k_idx == idx, 1, jnp.where(k_idx > idx, 2, 0))
    return jax.lax.switch(branch, [call(False), call(True), ahead], *args)


def _rf_block_bwd(qt, k_blk, v_blk, out_t, lse, do_t, kvm, k_idx, idx,
                  causal, bq, bk, interpret):
    """One rotation's backward kernels -> (dq_t [B,H,Tq,D],
    dk/dv [B,Tk,Hkv,D]) f32 partial contributions, computed against the
    FINAL (out, lse)."""
    from tensorlink_tpu.ops.pallas.flash_attention import flash_attention_bwd

    kt, vt = k_blk.swapaxes(1, 2), v_blk.swapaxes(1, 2)
    args = (qt, kt, vt) if kvm is None else (qt, kt, vt, kvm)

    def call(is_causal):
        def f(qt_, kt_, vt_, *m):
            dq, dk, dv = flash_attention_bwd(
                qt_, kt_, vt_, out_t, lse, do_t, m[0] if m else None,
                causal=is_causal, block_q=bq, block_k=bk,
                interpret=interpret,
            )
            return (
                dq.astype(jnp.float32),
                dk.swapaxes(1, 2).astype(jnp.float32),
                dv.swapaxes(1, 2).astype(jnp.float32),
            )

        return f

    def ahead(qt_, kt_, vt_, *m):
        return (
            jnp.zeros(qt_.shape, jnp.float32),
            jnp.zeros((kt_.shape[0], kt_.shape[2], kt_.shape[1], kt_.shape[3]),
                      jnp.float32),
            jnp.zeros((vt_.shape[0], vt_.shape[2], vt_.shape[1], vt_.shape[3]),
                      jnp.float32),
        )

    if not causal:
        return call(False)(*args)
    branch = jnp.where(k_idx == idx, 1, jnp.where(k_idx > idx, 2, 0))
    return jax.lax.switch(branch, [call(False), call(True), ahead], *args)


def _rf_fwd(q, k, v, kv_mask, causal, axis, interpret):
    from tensorlink_tpu.ops.flash import flash_block_for
    from tensorlink_tpu.ops.pallas.flash_attention import LSE_MASKED

    S = jax.lax.axis_size(axis)
    idx = jax.lax.axis_index(axis)
    B, Tq, H, D = q.shape
    Tk = k.shape[1]
    bq, bk = flash_block_for(Tq, B), flash_block_for(Tk, B)
    qt = q.swapaxes(1, 2)  # [B, H, Tq, D]
    perm = [(i, (i - 1) % S) for i in range(S)]

    def kvm_at(k_idx):
        if kv_mask is None:
            return None
        return jax.lax.dynamic_slice_in_dim(kv_mask, k_idx * Tk, Tk, axis=1)

    def merge(carry, o_blk, lse_blk):
        out_acc, lse_acc = carry
        lse_new = jnp.logaddexp(lse_acc, lse_blk)
        # both -inf (row fully masked so far): weights are 0, not nan
        w_old = jnp.where(
            jnp.isfinite(lse_new), jnp.exp(lse_acc - lse_new), 0.0
        )
        w_blk = jnp.where(
            jnp.isfinite(lse_new), jnp.exp(lse_blk - lse_new), 0.0
        )
        return (
            out_acc * w_old[..., None] + o_blk * w_blk[..., None],
            lse_new,
        )

    out0 = jnp.zeros((B, H, Tq, D), jnp.float32)
    lse0 = jnp.full((B, H, Tq), -jnp.inf, jnp.float32)
    o, l = _rf_block_fwd(
        qt, k, v, kvm_at(idx), idx, idx, causal, bq, bk, interpret
    )
    carry = merge((out0, lse0), o, l)

    def step(carry_kv, r):
        carry, k_blk, v_blk = carry_kv
        k_blk = jax.lax.ppermute(k_blk, axis, perm)
        v_blk = jax.lax.ppermute(v_blk, axis, perm)
        k_idx = (idx + r) % S
        o, l = _rf_block_fwd(
            qt, k_blk, v_blk, kvm_at(k_idx), k_idx, idx, causal, bq, bk,
            interpret,
        )
        return (merge(carry, o, l), k_blk, v_blk), None

    if S > 1:
        (carry, _, _), _ = jax.lax.scan(step, (carry, k, v), jnp.arange(1, S))
    out_t, lse = carry
    out = out_t.swapaxes(1, 2).astype(q.dtype)
    # backward kernels expect the single-kernel masked-row convention
    lse_saved = jnp.where(jnp.isfinite(lse), lse, LSE_MASKED)
    return out, (q, k, v, kv_mask, out_t.astype(q.dtype), lse_saved)


def _rf_bwd(causal, axis, interpret, res, g):
    from tensorlink_tpu.ops.flash import flash_block_for

    q, k, v, kv_mask, out_t, lse = res
    S = jax.lax.axis_size(axis)
    idx = jax.lax.axis_index(axis)
    Tq, Tk = q.shape[1], k.shape[1]
    bq, bk = (
        flash_block_for(Tq, q.shape[0]), flash_block_for(Tk, q.shape[0])
    )
    qt = q.swapaxes(1, 2)
    do_t = g.swapaxes(1, 2)
    perm = [(i, (i - 1) % S) for i in range(S)]

    def kvm_at(k_idx):
        if kv_mask is None:
            return None
        return jax.lax.dynamic_slice_in_dim(kv_mask, k_idx * Tk, Tk, axis=1)

    def step(carry, r):
        k_blk, v_blk, dk_acc, dv_acc, dq_acc = carry
        k_idx = (idx + r) % S
        dq_r, dk_r, dv_r = _rf_block_bwd(
            qt, k_blk, v_blk, out_t, lse, do_t, kvm_at(k_idx), k_idx, idx,
            causal, bq, bk, interpret,
        )
        dq_acc = dq_acc + dq_r
        dk_acc = dk_acc + dk_r
        dv_acc = dv_acc + dv_r
        # accumulators travel WITH their block: after the final hop of
        # the scan each dk/dv has collected all S contributions and sits
        # at its owner again (S rotations total)
        k_blk = jax.lax.ppermute(k_blk, axis, perm)
        v_blk = jax.lax.ppermute(v_blk, axis, perm)
        dk_acc = jax.lax.ppermute(dk_acc, axis, perm)
        dv_acc = jax.lax.ppermute(dv_acc, axis, perm)
        return (k_blk, v_blk, dk_acc, dv_acc, dq_acc), None

    zero_kv = jnp.zeros(k.shape, jnp.float32)
    carry = (k, v, zero_kv, jnp.zeros(v.shape, jnp.float32),
             jnp.zeros(qt.shape, jnp.float32))
    (_, _, dk, dv, dq_t), _ = jax.lax.scan(step, carry, jnp.arange(S))
    dq = dq_t.swapaxes(1, 2).astype(q.dtype)
    dmask = None if kv_mask is None else jnp.zeros_like(kv_mask)
    return dq, dk.astype(k.dtype), dv.astype(v.dtype), dmask


@partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6))
def ring_flash_attention(q, k, v, kv_mask=None, causal: bool = False,
                         axis: str = "seq", interpret: bool = False):
    """Ring attention whose local block math IS the Pallas flash kernel.
    Call INSIDE shard_map over ``axis``. q [B, Tq, H, D]; k, v
    [B, Tk, Hkv, D] — GQA stays NARROW on the ring (kernels read groups
    via index maps), unlike the einsum ring's pre-repeat. ``kv_mask`` is
    the GLOBAL [B, S*Tk] key-validity vector (nonzero = attend) or None.
    Differentiable via the blockwise backward kernels."""
    return _rf_fwd(q, k, v, kv_mask, causal, axis, interpret)[0]


ring_flash_attention.defvjp(_rf_fwd, _rf_bwd)


def _ring_flash_usable(q, k, mask, interpret) -> tuple:
    """(kv_mask | None, usable: bool) — kernel path preconditions: TPU
    (or interpret), tile-able local lengths, mask absent or a global
    key-padding vector [B, 1, 1, S*Tk]."""
    from tensorlink_tpu.ops.flash import _tile_ok, _use_pallas

    if not (_use_pallas(interpret) and _tile_ok(q.shape[1])
            and _tile_ok(k.shape[1])):
        return None, False
    if mask is None:
        return None, True
    if mask.ndim == 4 and mask.shape[1] == 1 and mask.shape[2] == 1:
        return mask[:, 0, 0, :].astype(jnp.float32), True
    return None, False  # square masks stay on the einsum ring


def _reject_unsupported(name: str, **kwargs):
    """ring/ulysses do not implement these attention kwargs; swallowing
    them via **_ would SILENTLY change semantics (full-context attention
    under a configured sliding window, default scaling under a custom
    scale, dropped position bias). MultiHeadAttention also rejects the
    combinations at construction; this guards direct callers."""
    for kw, val in kwargs.items():
        if val is not None:
            raise NotImplementedError(
                f"{name} attention does not support {kw}="
                f"{val!r} (use the reference or flash impl)"
            )


def ring_attention_impl(q, k, v, *, causal=False, mask=None, q_offset=0,
                        interpret=False, window=None, bias=None, scale=None,
                        **_):
    """Drop-in ``attn_impl`` for MultiHeadAttention ("ring"), to be used
    INSIDE a shard_map that binds the ``seq`` axis (the engine's Pipeline
    with seq>1). q,k,v are the LOCAL [B, T/seq, H, D] shards; attention
    runs over the full sequence by rotating K/V around the ring.

    Local block compute takes the Pallas flash path when the kernels can
    run (TPU/interpret + tile-able shapes + padding-vector or no mask);
    otherwise the einsum ring. ``mask``, when given, must be the GLOBAL
    full-sequence mask replicated across the seq axis (the engine's
    extras channel ships it that way); each rotation slices the k-block's
    columns. KV caches are not expressible on the ring path (decode runs
    unsharded)."""
    if not (isinstance(q_offset, int) and q_offset == 0):
        raise NotImplementedError(
            "ring attention does not support caches — sequence-"
            "sharded SERVING goes through InferenceEngine("
            "kv_seq_shard=True), which shards the KV cache's slot "
            "dim over the seq axis and lets the SPMD partitioner "
            "derive the online-softmax merge collectives"
        )
    _reject_unsupported("ring", window=window, bias=bias, scale=scale)
    S = jax.lax.axis_size("seq")
    if mask is not None and mask.shape[3] != S * k.shape[1]:
        raise ValueError(
            f"ring mask must be GLOBAL: last dim {mask.shape[3]} != "
            f"axis_size*Tk_local = {S * k.shape[1]} (a token-sharded mask "
            "cannot follow the rotating k-blocks)"
        )
    kv_vec, usable = _ring_flash_usable(q, k, mask, interpret)
    if usable:
        return ring_flash_attention(q, k, v, kv_vec, causal, "seq", interpret)
    H, Hkv = q.shape[2], k.shape[2]
    if Hkv != H:  # GQA: repeat (the einsum ring rotates whole K/V shards)
        k = jnp.repeat(k, H // Hkv, axis=2)
        v = jnp.repeat(v, H // Hkv, axis=2)
    return ring_attention_local(q, k, v, axis="seq", causal=causal, mask=mask)


def ring_attention(
    q: jax.Array,  # [B, T, H, D] global
    k: jax.Array,
    v: jax.Array,
    mesh: Mesh,
    *,
    axis: str = "seq",
    causal: bool = False,
    mask: jax.Array | None = None,  # [B, 1, 1|T, T] global, replicated
    use_flash: bool = False,
    interpret: bool = False,
):
    """Global entry: shards the T dim over ``axis`` and runs the ring.
    The optional mask stays replicated — each rotation slices it at the
    k-block's global offset. ``use_flash`` routes the local block math
    through the Pallas kernels (ring_flash_attention; mask must then be
    a key-padding vector form or None). Differentiable; jit at the call
    site."""
    has_mask = mask is not None

    def local(q_, k_, v_, *m_):
        m = m_[0] if m_ else None
        if use_flash:
            kv_vec, usable = _ring_flash_usable(q_, k_, m, interpret)
            if not usable:
                raise NotImplementedError(
                    "use_flash=True needs TPU/interpret, tile-able local "
                    "lengths, and a key-padding-vector mask ([B,1,1,T]) "
                    "or none — square masks run on the einsum ring"
                )
            return ring_flash_attention(
                q_, k_, v_, kv_vec, causal, axis, interpret
            )
        return ring_attention_local(q_, k_, v_, axis=axis, causal=causal, mask=m)

    fn = jax.shard_map(
        local,
        mesh=mesh,
        in_specs=(P(None, axis), P(None, axis), P(None, axis))
        + ((P(),) if has_mask else ()),
        out_specs=P(None, axis),
        axis_names=frozenset({axis}),
        check_vma=False,
    )
    return fn(q, k, v, *((mask,) if has_mask else ()))


# --------------------------------------------------------------- Ulysses
# DeepSpeed-Ulysses-style sequence parallelism (SURVEY §2.3 SP row):
# instead of rotating K/V around a ring, TWO all_to_all collectives swap
# the sharded dimension — tokens in, heads out — so each device computes
# FULL-sequence attention for H/S of the heads with any off-the-shelf
# kernel. Trade-offs vs the ring: supports padding masks (every device
# sees all tokens), one dense collective instead of S-1 overlapped hops,
# requires num_heads divisible by the axis size, and peak activation
# memory is the full sequence for its head slice.


def ulysses_attention_local(
    q: jax.Array,  # [B, T/S, H, D] local shard
    k: jax.Array,  # [B, T/S, Hkv, D] — GQA kept narrow when Hkv % S == 0
    v: jax.Array,
    *,
    axis: str = "seq",
    causal: bool = False,
    mask=None,  # [B, 1, 1, T] GLOBAL (replicated) key-padding mask
) -> jax.Array:
    """Call INSIDE shard_map over ``axis``. all_to_all head/sequence swap,
    full-sequence attention locally, swap back.

    ``mask``, when given, must be replicated and global-length (the
    standalone ``ulysses_attention`` entry does this); a token-sharded
    mask shard would not broadcast against the post-swap [.., T, T]
    logits. K/V swap at their OWN head count when it divides the axis
    (post-swap contiguous head blocks align with GQA grouping), so GQA
    ships Hkv/H-th the collective bytes of a pre-repeat."""
    S = jax.lax.axis_size(axis)
    H, Hkv = q.shape[2], k.shape[2]
    if H % S:
        raise ValueError(f"num_heads {H} not divisible by seq axis size {S}")
    if Hkv != H and Hkv % S:
        # uneven kv-head split: fall back to shipping repeated K/V
        k = jnp.repeat(k, H // Hkv, axis=2)
        v = jnp.repeat(v, H // Hkv, axis=2)

    def swap_in(x):  # [B, T/S, h, D] -> [B, T, h/S, D]
        return jax.lax.all_to_all(x, axis, split_axis=2, concat_axis=1,
                                  tiled=True)

    def swap_out(x):  # [B, T, H/S, D] -> [B, T/S, H, D]
        return jax.lax.all_to_all(x, axis, split_axis=1, concat_axis=2,
                                  tiled=True)

    if mask is None:
        # flash path (falls back to the einsum off-TPU / short seq): the
        # whole point of Ulysses is long context, where materializing
        # [B, H/S, T, T] logits is exactly the blowup to avoid
        from tensorlink_tpu.ops.flash import flash_attention_impl as attn
    else:
        from tensorlink_tpu.nn.attention import dot_product_attention as attn
    out = attn(swap_in(q), swap_in(k), swap_in(v), causal=causal, mask=mask)
    return swap_out(out)


def ulysses_attention_impl(q, k, v, *, causal=False, mask=None, q_offset=0,
                           window=None, bias=None, scale=None, **_):
    """Drop-in ``attn_impl`` ("ulysses") for MultiHeadAttention inside a
    shard_map binding the ``seq`` axis. KV caches are not supported
    (decode runs unsharded). ``mask``, when given, must be the GLOBAL
    full-sequence mask replicated across the axis (head dim 1) — the
    engine's extras channel ships it that way; a token-SHARDED mask
    cannot be applied to the post-swap full-sequence logits."""
    if not (isinstance(q_offset, int) and q_offset == 0):
        raise NotImplementedError(
            "ulysses attention does not support caches — see "
            "InferenceEngine(kv_seq_shard=True) for sequence-"
            "sharded serving"
        )
    _reject_unsupported("ulysses", window=window, bias=bias, scale=scale)
    if mask is not None:
        S = jax.lax.axis_size("seq")
        if mask.shape[1] != 1:
            raise NotImplementedError(
                "ulysses attention supports masks with head dim 1 only "
                "(heads are split across the axis after the swap)"
            )
        if mask.shape[3] != S * q.shape[1]:
            raise ValueError(
                f"ulysses mask must be GLOBAL: last dim {mask.shape[3]} "
                f"!= axis_size*T_local = {S * q.shape[1]}"
            )
    return ulysses_attention_local(q, k, v, axis="seq", causal=causal, mask=mask)


def ulysses_attention(
    q: jax.Array,  # [B, T, H, D] global
    k: jax.Array,
    v: jax.Array,
    mesh: Mesh,
    *,
    axis: str = "seq",
    causal: bool = False,
    mask=None,
):
    """Global entry: shards the T dim over ``axis`` and runs the
    all_to_all swap. The (optional) key-padding mask is replicated — every
    device applies it over the full sequence after the swap.
    Differentiable; jit at the call site."""
    has_mask = mask is not None
    seq_spec = P(None, axis)
    fn = jax.shard_map(
        lambda q_, k_, v_, *m_: ulysses_attention_local(
            q_, k_, v_, axis=axis, causal=causal,
            mask=m_[0] if m_ else None,
        ),
        mesh=mesh,
        in_specs=(seq_spec, seq_spec, seq_spec) + ((P(),) if has_mask else ()),
        out_specs=seq_spec,
        axis_names=frozenset({axis}),
        check_vma=False,
    )
    return fn(q, k, v, *((mask,) if has_mask else ()))
