"""Sequence/context parallelism: ring attention over the ``seq`` mesh axis.

The reference has no sequence-dimension handling at all (survey §5.7: the
only split anywhere is torch.chunk on the batch dim). For long-context
training the sequence is sharded over the ``seq`` axis; each device holds a
[B, T/S, H, D] slice of q,k,v. Attention over the full sequence is computed
by rotating the K/V block around the ring with `lax.ppermute` S times while
accumulating online-softmax statistics — ICI traffic overlaps with the
block attention compute, and peak memory is one K/V block instead of the
full sequence.

Causal masking uses each block's global offset: a k-block strictly ahead of
the local q-block contributes nothing (masked), the diagonal block gets the
triangular mask, and blocks behind are unmasked. Differentiable end-to-end
(ppermute transposes to the reverse rotation).
"""

from __future__ import annotations

from functools import partial
from typing import Callable

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

NEG_INF = -1e30


def _block_attn(q, k, v, q_off, k_off, causal, scale, mask=None):
    """One blockwise attention accumulation step.

    q: [B, Tq, H, D]; k,v: [B, Tk, H, D]. ``mask``, when given, is the
    GLOBAL (replicated) [B, 1, 1|Tglobal, Tglobal] boolean mask; the
    k-block's (and, for a square mask, the q-block's) slice is taken at
    the block offsets. Returns the masked logits [B, H, Tq, Tk].
    """
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k).astype(jnp.float32) * scale
    Tq, Tk = q.shape[1], k.shape[1]
    if causal:
        qpos = q_off + jnp.arange(Tq)[:, None]
        kpos = k_off + jnp.arange(Tk)[None, :]
        keep = qpos >= kpos
        s = jnp.where(keep[None, None], s, NEG_INF)
    if mask is not None:
        mblk = jax.lax.dynamic_slice_in_dim(mask, k_off, Tk, axis=3)
        if mask.shape[2] != 1:  # square mask: also slice the q dim
            mblk = jax.lax.dynamic_slice_in_dim(mblk, q_off, Tq, axis=2)
        s = jnp.where(mblk, s, NEG_INF)
    return s


def ring_attention_local(
    q: jax.Array,  # [B, Tq_local, H, D]
    k: jax.Array,  # [B, Tk_local, H, D]
    v: jax.Array,
    *,
    axis: str = "seq",
    causal: bool = False,
    mask: jax.Array | None = None,  # GLOBAL replicated [B,1,1|T,T] bool
) -> jax.Array:
    """Call INSIDE shard_map over ``axis``. Full-sequence attention for the
    local q shard, K/V rotating around the ring. ``mask`` must be the
    full-sequence mask replicated across the axis (head dim 1); each
    rotation slices the k-block's columns at its global offset, so padded
    workloads can sequence-shard (VERDICT r3 weak #6)."""
    S = jax.lax.axis_size(axis)
    idx = jax.lax.axis_index(axis)
    B, Tq, H, D = q.shape
    Tk = k.shape[1]
    if mask is not None:
        if mask.shape[1] != 1:
            raise NotImplementedError(
                "ring attention supports masks with head dim 1 only"
            )
        if mask.shape[3] != S * Tk:
            raise ValueError(
                f"ring mask must be GLOBAL: last dim {mask.shape[3]} != "
                f"axis_size*Tk_local = {S * Tk} (a token-sharded mask "
                "cannot follow the rotating k-blocks)"
            )
    scale = D ** -0.5
    q_off = idx * Tq

    m = jnp.full((B, H, Tq, 1), NEG_INF, jnp.float32)
    l = jnp.zeros((B, H, Tq, 1), jnp.float32)
    acc = jnp.zeros((B, Tq, H, D), jnp.float32)
    # ring: receive from the next rank, so after r rotations we hold shard
    # (idx + r) % S
    perm = [(i, (i - 1) % S) for i in range(S)]

    def accumulate(carry, k_blk, v_blk, r):
        m, l, acc = carry
        k_off = ((idx + r) % S) * Tk
        s = _block_attn(q, k_blk, v_blk, q_off, k_off, causal, scale, mask)
        m_cur = jnp.max(s, axis=-1, keepdims=True)
        m_new = jnp.maximum(m, m_cur)
        p = jnp.exp(s - m_new)
        if causal or mask is not None:
            p = jnp.where(s <= NEG_INF / 2, 0.0, p)
        alpha = jnp.exp(m - m_new)
        l = alpha * l + jnp.sum(p, axis=-1, keepdims=True)
        pv = jnp.einsum("bhqk,bkhd->bqhd", p.astype(v_blk.dtype), v_blk).astype(
            jnp.float32
        )
        acc = acc * alpha.transpose(0, 2, 1, 3) + pv
        return (m_new, l, acc)

    # local block first (no collective), then S-1 rotate-and-accumulate
    # steps — exactly S-1 ppermute pairs, none wasted.
    carry = accumulate((m, l, acc), k, v, 0)

    def step(carry_kv, r):
        carry, k_blk, v_blk = carry_kv
        k_blk = jax.lax.ppermute(k_blk, axis, perm)
        v_blk = jax.lax.ppermute(v_blk, axis, perm)
        carry = accumulate(carry, k_blk, v_blk, r)
        return (carry, k_blk, v_blk), None

    if S > 1:
        (carry, _, _), _ = jax.lax.scan(
            step, (carry, k, v), jnp.arange(1, S)
        )
    m, l, acc = carry
    l_safe = jnp.where(l == 0.0, 1.0, l)
    out = acc / l_safe.transpose(0, 2, 1, 3)
    return out.astype(q.dtype)


def ring_attention_impl(q, k, v, *, causal=False, mask=None, q_offset=0, **_):
    """Drop-in ``attn_impl`` for MultiHeadAttention ("ring"), to be used
    INSIDE a shard_map that binds the ``seq`` axis (the engine's Pipeline
    with seq>1). q,k,v are the LOCAL [B, T/seq, H, D] shards; attention
    runs over the full sequence by rotating K/V around the ring.

    ``mask``, when given, must be the GLOBAL full-sequence mask
    replicated across the seq axis (the engine's extras channel ships it
    that way); each rotation slices the k-block's columns. KV caches are
    not expressible on the ring path (decode runs unsharded).
    """
    if not (isinstance(q_offset, int) and q_offset == 0):
        raise NotImplementedError("ring attention does not support caches")
    H, Hkv = q.shape[2], k.shape[2]
    if Hkv != H:  # GQA: repeat (ring rotates whole K/V shards)
        k = jnp.repeat(k, H // Hkv, axis=2)
        v = jnp.repeat(v, H // Hkv, axis=2)
    return ring_attention_local(q, k, v, axis="seq", causal=causal, mask=mask)


def ring_attention(
    q: jax.Array,  # [B, T, H, D] global
    k: jax.Array,
    v: jax.Array,
    mesh: Mesh,
    *,
    axis: str = "seq",
    causal: bool = False,
    mask: jax.Array | None = None,  # [B, 1, 1|T, T] global, replicated
):
    """Global entry: shards the T dim over ``axis`` and runs the ring.
    The optional mask stays replicated — each rotation slices it at the
    k-block's global offset. Differentiable; jit at the call site."""
    has_mask = mask is not None
    fn = jax.shard_map(
        lambda q_, k_, v_, *m_: ring_attention_local(
            q_, k_, v_, axis=axis, causal=causal,
            mask=m_[0] if m_ else None,
        ),
        mesh=mesh,
        in_specs=(P(None, axis), P(None, axis), P(None, axis))
        + ((P(),) if has_mask else ()),
        out_specs=P(None, axis),
        axis_names=frozenset({axis}),
        check_vma=False,
    )
    return fn(q, k, v, *((mask,) if has_mask else ()))


# --------------------------------------------------------------- Ulysses
# DeepSpeed-Ulysses-style sequence parallelism (SURVEY §2.3 SP row):
# instead of rotating K/V around a ring, TWO all_to_all collectives swap
# the sharded dimension — tokens in, heads out — so each device computes
# FULL-sequence attention for H/S of the heads with any off-the-shelf
# kernel. Trade-offs vs the ring: supports padding masks (every device
# sees all tokens), one dense collective instead of S-1 overlapped hops,
# requires num_heads divisible by the axis size, and peak activation
# memory is the full sequence for its head slice.


def ulysses_attention_local(
    q: jax.Array,  # [B, T/S, H, D] local shard
    k: jax.Array,  # [B, T/S, Hkv, D] — GQA kept narrow when Hkv % S == 0
    v: jax.Array,
    *,
    axis: str = "seq",
    causal: bool = False,
    mask=None,  # [B, 1, 1, T] GLOBAL (replicated) key-padding mask
) -> jax.Array:
    """Call INSIDE shard_map over ``axis``. all_to_all head/sequence swap,
    full-sequence attention locally, swap back.

    ``mask``, when given, must be replicated and global-length (the
    standalone ``ulysses_attention`` entry does this); a token-sharded
    mask shard would not broadcast against the post-swap [.., T, T]
    logits. K/V swap at their OWN head count when it divides the axis
    (post-swap contiguous head blocks align with GQA grouping), so GQA
    ships Hkv/H-th the collective bytes of a pre-repeat."""
    S = jax.lax.axis_size(axis)
    H, Hkv = q.shape[2], k.shape[2]
    if H % S:
        raise ValueError(f"num_heads {H} not divisible by seq axis size {S}")
    if Hkv != H and Hkv % S:
        # uneven kv-head split: fall back to shipping repeated K/V
        k = jnp.repeat(k, H // Hkv, axis=2)
        v = jnp.repeat(v, H // Hkv, axis=2)

    def swap_in(x):  # [B, T/S, h, D] -> [B, T, h/S, D]
        return jax.lax.all_to_all(x, axis, split_axis=2, concat_axis=1,
                                  tiled=True)

    def swap_out(x):  # [B, T, H/S, D] -> [B, T/S, H, D]
        return jax.lax.all_to_all(x, axis, split_axis=1, concat_axis=2,
                                  tiled=True)

    if mask is None:
        # flash path (falls back to the einsum off-TPU / short seq): the
        # whole point of Ulysses is long context, where materializing
        # [B, H/S, T, T] logits is exactly the blowup to avoid
        from tensorlink_tpu.ops.flash import flash_attention_impl as attn
    else:
        from tensorlink_tpu.nn.attention import dot_product_attention as attn
    out = attn(swap_in(q), swap_in(k), swap_in(v), causal=causal, mask=mask)
    return swap_out(out)


def ulysses_attention_impl(q, k, v, *, causal=False, mask=None, q_offset=0, **_):
    """Drop-in ``attn_impl`` ("ulysses") for MultiHeadAttention inside a
    shard_map binding the ``seq`` axis. KV caches are not supported
    (decode runs unsharded). ``mask``, when given, must be the GLOBAL
    full-sequence mask replicated across the axis (head dim 1) — the
    engine's extras channel ships it that way; a token-SHARDED mask
    cannot be applied to the post-swap full-sequence logits."""
    if not (isinstance(q_offset, int) and q_offset == 0):
        raise NotImplementedError("ulysses attention does not support caches")
    if mask is not None:
        S = jax.lax.axis_size("seq")
        if mask.shape[1] != 1:
            raise NotImplementedError(
                "ulysses attention supports masks with head dim 1 only "
                "(heads are split across the axis after the swap)"
            )
        if mask.shape[3] != S * q.shape[1]:
            raise ValueError(
                f"ulysses mask must be GLOBAL: last dim {mask.shape[3]} "
                f"!= axis_size*T_local = {S * q.shape[1]}"
            )
    return ulysses_attention_local(q, k, v, axis="seq", causal=causal, mask=mask)


def ulysses_attention(
    q: jax.Array,  # [B, T, H, D] global
    k: jax.Array,
    v: jax.Array,
    mesh: Mesh,
    *,
    axis: str = "seq",
    causal: bool = False,
    mask=None,
):
    """Global entry: shards the T dim over ``axis`` and runs the
    all_to_all swap. The (optional) key-padding mask is replicated — every
    device applies it over the full sequence after the swap.
    Differentiable; jit at the call site."""
    has_mask = mask is not None
    seq_spec = P(None, axis)
    fn = jax.shard_map(
        lambda q_, k_, v_, *m_: ulysses_attention_local(
            q_, k_, v_, axis=axis, causal=causal,
            mask=m_[0] if m_ else None,
        ),
        mesh=mesh,
        in_specs=(seq_spec, seq_spec, seq_spec) + ((P(),) if has_mask else ()),
        out_specs=seq_spec,
        axis_names=frozenset({axis}),
        check_vma=False,
    )
    return fn(q, k, v, *((mask,) if has_mask else ()))
