"""Mixture-of-Experts feed-forward with expert parallelism.

The reference has no MoE/expert parallelism at all (survey §2.3: "EP —
absent"); this is TPU-native from scratch. Design:

- Experts are ONE stacked param tree with a leading [E, ...] axis, sharded
  over the mesh's ``model`` axis (`P(model, ...)`) — expert parallelism is
  just tensor sharding on that axis. No per-expert Python modules, no
  host-side routing.
- **Collective lowering** (both verified against compiled HLO in
  tests/test_moe.py): with no ambient mesh the partitioner falls back to
  all-gather (tokens to the expert shards) + all-reduce (partial combine
  outputs) — O(E)-redundant ICI traffic and compute. When an ambient mesh
  (``jax.set_mesh``) carries ``ep_axis``, `apply` additionally shards the
  token-group dim over (data, ep_axis) and pins the dispatched [E, G, C, D]
  tensor to `P(ep_axis, ...)`: the group->expert reshard then compiles to
  **all_to_all** over ``ep_axis`` (t5x/GShard-style), each device routes
  and computes only its 1/N token slice, and the redundant gather/reduce
  pair disappears. The module stays mesh-agnostic: the ambient mesh is
  read at trace time (`jax.sharding.get_abstract_mesh()`), only
  Auto-partitioned axes are used (so it composes inside the pipeline
  shard_map, where ``pipe``/``seq`` are Manual), and with no mesh in
  context behavior is bit-identical to the fallback.
- Token-choice top-k routing (Switch/GShard style) with a capacity
  factor: position-in-expert comes from a cumulative sum over the token
  axis, overflow tokens are dropped (their residual path carries them).
- The router's auxiliary load-balancing loss (mean fraction x mean
  probability per expert, scaled by E) is returned alongside the output
  so trainers can add ``aux_weight * aux_loss``.

Everything is dense einsum algebra on one-hot dispatch tensors —
MXU-shaped, static shapes, no data-dependent control flow.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from tensorlink_tpu.nn.module import Module, register_module_type
from tensorlink_tpu.nn.layers import _lecun_normal, _normal


def _auto_ambient_axes() -> tuple:
    """Names of ambient-mesh axes the SPMD partitioner controls (Auto).

    Manual axes (bound by an enclosing shard_map — the engine's ``pipe``/
    ``seq``) must not appear in a with_sharding_constraint spec; Explicit
    axes would need explicit-sharding plumbing this module doesn't do.
    Empty when no ``jax.set_mesh`` context is active."""
    mesh = jax.sharding.get_abstract_mesh()
    if mesh.empty:
        return ()
    return tuple(
        name
        for name, t in zip(mesh.axis_names, mesh.axis_types)
        if t == jax.sharding.AxisType.Auto
    )


@register_module_type
class MoEFeedForward(Module):
    """Drop-in replacement for FeedForward: [B, T, D] -> [B, T, D].

    ``apply`` returns just the output; ``apply_with_aux`` returns
    ``(output, aux_loss)`` for load-balanced training.
    """

    def __init__(
        self,
        dim: int,
        hidden_dim: int,
        num_experts: int = 8,
        top_k: int = 2,
        capacity_factor: float = 1.25,
        gated: bool = True,
        router_noise: float = 0.0,
        activation: str = "gelu",
        ep_axis: str | None = "model",
    ):
        super().__init__()
        self.dim = dim
        self.hidden_dim = hidden_dim
        self.num_experts = num_experts
        self.top_k = top_k
        self.capacity_factor = capacity_factor
        self.gated = gated
        self.router_noise = router_noise
        self.activation = activation
        # mesh axis the all_to_all dispatch rides (module docstring);
        # engages only when an ambient mesh carries it as an Auto axis
        self.ep_axis = ep_axis

    def init(self, key):
        E, D, H = self.num_experts, self.dim, self.hidden_dim
        kr, ku, kg, kd = jax.random.split(key, 4)
        params = {
            "router": {"w": _normal(kr, (D, E))},
            "up": _lecun_normal(ku, (E, D, H), fan_in=D),
            "down": _lecun_normal(kd, (E, H, D), fan_in=H),
        }
        if self.gated:
            params["gate"] = _lecun_normal(kg, (E, D, H), fan_in=D)
        return params

    def param_spec(self, model_axis: str = "model"):
        spec = {
            "router": {"w": P()},
            # expert axis sharded: this IS expert parallelism (each
            # device computes only its experts; see module docstring for
            # the measured collective lowering)
            "up": P(model_axis, None, None),
            "down": P(model_axis, None, None),
        }
        if self.gated:
            spec["gate"] = P(model_axis, None, None)
        return spec

    def capacity(self, tokens_per_group: int) -> int:
        c = int(self.capacity_factor * self.top_k * tokens_per_group
                / self.num_experts)
        return max(c, 1)

    def _route(self, logits, rng=None, train=False):
        """logits [B, T, E] -> (dispatch [B, T, E, C], combine [B, T, E, C],
        aux_loss). Top-k with per-expert capacity."""
        B, T, E = logits.shape
        C = self.capacity(T)
        if train and self.router_noise > 0 and rng is not None:
            logits = logits + self.router_noise * jax.random.normal(
                rng, logits.shape, logits.dtype
            )
        probs = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)

        dispatch = jnp.zeros((B, T, E, C), jnp.float32)
        combine = jnp.zeros((B, T, E, C), jnp.float32)
        # running per-expert fill, so expert k=2 choices respect capacity
        # consumed by k=1 choices
        fill = jnp.zeros((B, E), jnp.int32)
        masked = probs
        importance = jnp.zeros((B, E), jnp.float32)
        for _ in range(self.top_k):
            idx = jnp.argmax(masked, axis=-1)  # [B, T]
            onehot = jax.nn.one_hot(idx, E, dtype=jnp.float32)  # [B, T, E]
            importance = importance + onehot.mean(axis=1)
            # position of each token within its chosen expert
            pos = jnp.cumsum(onehot, axis=1) - onehot + fill[:, None, :]
            pos = jnp.sum(pos * onehot, axis=-1).astype(jnp.int32)  # [B, T]
            keep = pos < C
            w = jnp.sum(probs * onehot, axis=-1) * keep  # [B, T]
            poh = jax.nn.one_hot(pos, C, dtype=jnp.float32)  # [B, T, C]
            sel = onehot[..., None] * poh[:, :, None, :]  # [B, T, E, C]
            dispatch = dispatch + sel * keep[..., None, None]
            combine = combine + sel * w[..., None, None]
            fill = fill + jnp.sum(
                onehot * keep[..., None], axis=1
            ).astype(jnp.int32)
            masked = masked * (1.0 - onehot)  # exclude chosen expert

        # normalize combine weights over the selected experts
        denom = combine.sum(axis=(2, 3), keepdims=True)
        combine = combine / jnp.maximum(denom, 1e-9)

        # GShard aux loss: E * mean(fraction_routed) . mean(router_prob)
        frac = importance / self.top_k  # [B, E] mean one-hot over tokens
        mean_prob = probs.mean(axis=1)  # [B, E]
        aux = E * jnp.mean(jnp.sum(frac * mean_prob, axis=-1))
        return dispatch, combine, aux

    def _ep_plan(self):
        """(group_spec_axes, ep_axis) for the all_to_all dispatch path, or
        (None, None) when no usable ambient mesh — see module docstring.
        Token groups (= batch rows; routing/capacity is per row) co-shard
        over ``data`` when present, so EP composes with DP: the reshard is
        an all_to_all over ``ep_axis`` inside each data slice."""
        if not self.ep_axis:
            return None, None
        axes = _auto_ambient_axes()
        if self.ep_axis not in axes:
            return None, None
        # dict.fromkeys dedupes while keeping order: ep_axis="data"
        # (EP over the DP axis) must not produce a duplicate-axis spec
        groups = tuple(
            a for a in dict.fromkeys(("data", self.ep_axis)) if a in axes
        )
        return groups, self.ep_axis

    def apply_with_aux(self, params, x, *, rng=None, train=False, **_):
        B, T, D = x.shape
        groups, ep = self._ep_plan()
        wsc = jax.lax.with_sharding_constraint
        if ep is not None:
            # each device routes only its token-group slice
            x = wsc(x, P(groups, None, None))
        logits = x.astype(jnp.float32) @ params["router"]["w"].astype(jnp.float32)
        dispatch, combine, aux = self._route(logits, rng=rng, train=train)
        dispatch = dispatch.astype(x.dtype)
        combine = combine.astype(x.dtype)
        if ep is not None:
            dispatch = wsc(dispatch, P(groups, None, None, None))

        # dispatch -> [E, B, C, D]; under SPMD with `up`/`down` sharded
        # on E each device computes this einsum only for its expert
        # shard (tokens reach it via all_to_all when the ambient-mesh
        # constraint below engages, else all-gather; see docstring)
        expert_in = jnp.einsum("btec,btd->ebcd", dispatch, x)
        if ep is not None:
            # group-sharded -> expert-sharded over the SAME mesh axis:
            # this is the pin that compiles to all_to_all
            data = tuple(a for a in groups if a != ep) or None
            expert_in = wsc(expert_in, P(ep, data, None, None))
        up = jnp.einsum("ebcd,edh->ebch", expert_in, params["up"].astype(x.dtype))
        if self.gated:
            g = jnp.einsum(
                "ebcd,edh->ebch", expert_in, params["gate"].astype(x.dtype)
            )
            h = jax.nn.silu(g) * up
        else:
            from tensorlink_tpu.nn.transformer import ACTIVATIONS

            h = ACTIVATIONS[self.activation](up)
        expert_out = jnp.einsum("ebch,ehd->ebcd", h, params["down"].astype(x.dtype))
        if ep is not None:
            # all_to_all back: every group re-collects its tokens, the
            # combine einsum below is then device-local per group
            expert_out = wsc(expert_out, P(None, groups, None, None))
        out = jnp.einsum("btec,ebcd->btd", combine, expert_out)
        return out, aux

    def apply(self, params, x, *, rng=None, train=False, **_):
        out, _ = self.apply_with_aux(params, x, rng=rng, train=train)
        return out

    def routing_stats(self, params, x) -> dict:
        """Router telemetry for benchmarks/monitoring: the fraction of
        (token, choice) routes dropped by the capacity limit (their
        residual path carries the token unchanged) and the aux loss.
        dispatch sums to the KEPT route count, so
        drop = 1 - sum(dispatch) / (B*T*top_k)."""
        B, T, _ = x.shape
        logits = x.astype(jnp.float32) @ params["router"]["w"].astype(
            jnp.float32
        )
        dispatch, _, aux = self._route(logits)
        kept = float(jnp.sum(dispatch))
        return {
            "drop_fraction": 1.0 - kept / (B * T * self.top_k),
            "aux_loss": float(aux),
            "capacity_per_expert": self.capacity(T),
        }
