"""Attention: reference jnp implementation + multi-head module.

Supports causal masking, padding masks, RoPE, grouped-query attention, and
incremental decoding with a KV cache. The inner kernel is pluggable so the
Pallas flash-attention kernel (ops/pallas/flash_attention.py) and ring
attention (parallel/sp.py) can drop in without touching module code.

Tensor-parallel layout is standard Megatron: q/k/v projections column-split
(heads spread over the `model` axis), output projection row-split, so one
psum per attention block.
"""

from __future__ import annotations

from typing import Callable

import jax
import jax.numpy as jnp

from tensorlink_tpu.nn.module import Module
from tensorlink_tpu.nn.layers import Dense


def band_keep(q_pos, k_pos, causal: bool, window: int | None):
    """THE positional attend predicate (one home for the edge
    convention — the reference path, the flash fallback's row-validity,
    and the Pallas kernels' per-block masks all call this): attend iff
    k <= q (causal) and k in (q-window, q]; symmetric band |q-k| <
    window when not causal. None = no positional constraint."""
    if not causal and window is None:
        return None
    keep = None
    if causal:
        keep = q_pos >= k_pos
    if window is not None:
        lo = k_pos > q_pos - window
        keep = lo if keep is None else jnp.logical_and(keep, lo)
        if not causal:
            keep = jnp.logical_and(keep, k_pos < q_pos + window)
    return keep


def dot_product_attention(
    q: jax.Array,  # [B, Tq, H, D]
    k: jax.Array,  # [B, Tk, Hkv, D]
    v: jax.Array,  # [B, Tk, Hkv, D]
    *,
    causal: bool = False,
    mask: jax.Array | None = None,  # [B, 1|H, Tq, Tk] bool, True=attend
    bias: jax.Array | None = None,
    q_offset: int | jax.Array = 0,
    scale: float | None = None,  # None = 1/sqrt(D); T5 uses 1.0
    window: int | None = None,  # sliding window: attend iff |q-k| < window
    **_,
) -> jax.Array:
    """Reference attention, f32 softmax. ``q_offset`` shifts query positions
    for causal masking during incremental decode (cache len Tk > Tq).

    ``window`` is Mistral-style sliding-window attention: a query at
    position i attends keys in (i-window, i] when causal, or the
    symmetric band |i-j| < window when not."""
    B, Tq, H, D = q.shape
    Hkv = k.shape[2]
    if Hkv != H:  # grouped-query: repeat kv heads
        rep = H // Hkv
        k = jnp.repeat(k, rep, axis=2)
        v = jnp.repeat(v, rep, axis=2)
    scale = D ** -0.5 if scale is None else scale
    logits = jnp.einsum("bqhd,bkhd->bhqk", q, k).astype(jnp.float32) * scale
    if causal or window is not None:
        Tk = k.shape[1]
        qpos = jnp.arange(Tq)[:, None] + q_offset
        kpos = jnp.arange(Tk)[None, :]
        keep = band_keep(qpos, kpos, causal, window)
        logits = jnp.where(keep[None, None], logits, -1e30)
    if mask is not None:
        logits = jnp.where(mask, logits, -1e30)
    if bias is not None:
        logits = logits + bias.astype(jnp.float32)
    probs = jax.nn.softmax(logits, axis=-1)
    return jnp.einsum("bhqk,bkhd->bqhd", probs.astype(v.dtype), v)


DECODE_BLOCK = 256
# windowless decode takes the bounded-blockwise loop only above this
# cache size (slots): below it, one full-width einsum beats the loop's
# per-layer launch overhead (see MultiHeadAttention.apply decode notes)
DECODE_BLOCKWISE_MIN_WINDOWLESS = 8 * DECODE_BLOCK


def decode_attention_blockwise(
    q: jax.Array,  # [B, Tq, H, D] — decode step (Tq==1) or verify-K chunk
    k: jax.Array,  # [B, L, Hkv, D] — full cache
    v: jax.Array,
    live_len: jax.Array,  # scalar int32: slots [0, live_len) may be real
    *,
    mask: jax.Array | None = None,  # [B, 1|H, 1|Tq, L] bool over cache slots
    block: int = DECODE_BLOCK,
    start: jax.Array | int = 0,  # first attendable slot (sliding window)
) -> jax.Array:
    """Length-bounded decode attention: online softmax over
    ceil(live_len / block) cache blocks via a dynamic-bound fori_loop, so
    per-token cost tracks the USED prefix (rounded up to ``block``), not
    the cache capacity — serving with max_len 2048 and a 100-token prompt
    no longer pays 2048 slots of score/mask work every step (VERDICT r3
    weak #8; the bench previously shrank the cache to dodge this).

    ``Tq > 1`` is the speculative verify-K form: the K+1 candidate
    queries share the block loop (live_len bounds the FARTHEST query;
    per-query causality must come from ``mask``), so a verify pass
    stays length-bounded exactly like the K+1 decode steps it replaces.

    Requires L % block == 0 (callers round the cache capacity up);
    validity/causality comes entirely from ``mask`` — slots at or beyond
    live_len MUST be masked False by the caller.
    """
    B, Tq, H, D = q.shape
    L = k.shape[1]
    if L % block:
        # not an assert: under python -O a violated contract would
        # silently double-count clamped slice overlap in the softmax
        raise ValueError(
            f"blockwise decode needs cache {L} % block {block} == 0"
        )
    Hkv = k.shape[2]
    rep = H // Hkv
    scale = D ** -0.5
    # clamp to capacity: a verify-K frontier within K slots of the
    # region end yields live_len up to L+K (the scatter DROPPED those
    # writes), and an unclamped bound would run one extra fori_loop
    # iteration whose clamped dynamic_slice re-adds the last block's
    # k/v and mask to the online softmax — double-counted mass,
    # silently wrong outputs for every row reaching the last block
    nb = jnp.minimum(
        (live_len.astype(jnp.int32) + block - 1) // block, L // block
    )
    # sliding window: blocks wholly below ``start`` are fully masked —
    # skip them so windowed decode cost tracks the WINDOW, not the
    # prefix (correctness still comes from ``mask``; this is pure skip)
    b0 = jnp.asarray(start, jnp.int32) // block

    m0 = jnp.full((B, H, Tq, 1), -1e30, jnp.float32)
    l0 = jnp.zeros((B, H, Tq, 1), jnp.float32)
    acc0 = jnp.zeros((B, Tq, H, D), jnp.float32)

    def body(j, carry):
        m, l, acc = carry
        start = j * block
        kb = jax.lax.dynamic_slice_in_dim(k, start, block, axis=1)
        vb = jax.lax.dynamic_slice_in_dim(v, start, block, axis=1)
        if rep != 1:
            kb = jnp.repeat(kb, rep, axis=2)
            vb = jnp.repeat(vb, rep, axis=2)
        s = jnp.einsum("bqhd,bkhd->bhqk", q, kb).astype(jnp.float32) * scale
        if mask is not None:
            mb = jax.lax.dynamic_slice_in_dim(mask, start, block, axis=3)
            s = jnp.where(mb, s, -1e30)
        m_new = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))
        p = jnp.exp(s - m_new)
        if mask is not None:
            p = jnp.where(mb, p, 0.0)
        alpha = jnp.exp(m - m_new)
        l = alpha * l + jnp.sum(p, axis=-1, keepdims=True)
        pv = jnp.einsum("bhqk,bkhd->bqhd", p.astype(vb.dtype), vb).astype(
            jnp.float32
        )
        acc = acc * alpha.transpose(0, 2, 1, 3) + pv
        return (m_new, l, acc)

    m, l, acc = jax.lax.fori_loop(b0, nb, body, (m0, l0, acc0))
    l_safe = jnp.where(l == 0.0, 1.0, l)
    return (acc / l_safe.transpose(0, 2, 1, 3)).astype(q.dtype)


def fuse_qkv_params(attn_params: dict, num_heads: int, num_kv_heads: int,
                    head_dim: int) -> dict:
    """Convert one attention param dict {"q","k","v","o"[...]} to the
    fused layout {"qkv","o"[...]}: per-kv-group interleave
    [D, G, (hq q-heads | k | v), Dh] flattened on the output dim —
    exactly MultiHeadAttention(qkv_fused=True)'s expectation, so
    separately-imported HF weights (or a trained separate-layout
    checkpoint) can serve through the fused projection. Extra keys
    (e.g. LoRA adapters) are not supported — fuse before surgery."""
    import numpy as _np

    G, hq = num_kv_heads, num_heads // num_kv_heads
    extra = set(attn_params) - {"q", "k", "v", "o"}
    if extra:
        raise ValueError(f"cannot fuse attention params with extras {extra}")

    def cat(name):
        qw = _np.asarray(attn_params["q"][name])
        kw = _np.asarray(attn_params["k"][name])
        vw = _np.asarray(attn_params["v"][name])
        lead = qw.shape[:-1]  # (D,) for w, () for b
        qw = qw.reshape(*lead, G, hq, head_dim)
        kw = kw.reshape(*lead, G, 1, head_dim)
        vw = vw.reshape(*lead, G, 1, head_dim)
        f = _np.concatenate([qw, kw, vw], axis=-2)
        return jnp.asarray(f.reshape(*lead, G * (hq + 2) * head_dim))

    qkv = {"w": cat("w")}
    if "b" in attn_params["q"]:
        qkv["b"] = cat("b")
    return {"qkv": qkv, "o": attn_params["o"]}


def apply_rope(x: jax.Array, positions: jax.Array, theta: float = 10000.0) -> jax.Array:
    """Rotary position embedding over the last dim. x: [B, T, H, D]."""
    D = x.shape[-1]
    half = D // 2
    freqs = 1.0 / (theta ** (jnp.arange(0, half, dtype=jnp.float32) / half))
    angles = positions[..., None].astype(jnp.float32) * freqs  # [B?, T, half]
    # broadcast to [B, T, 1, half]
    while angles.ndim < x.ndim:
        angles = angles[..., None, :] if angles.ndim == x.ndim - 1 else angles[None]
    cos, sin = jnp.cos(angles), jnp.sin(angles)
    x1, x2 = x[..., :half], x[..., half:]
    out = jnp.concatenate([x1 * cos - x2 * sin, x2 * cos + x1 * sin], axis=-1)
    return out.astype(x.dtype)


def resolve_attn_impl(impl) -> Callable:
    """Map an ``attn_impl`` name to its kernel. Strings keep the choice
    serializable through ``Module.config()`` spec-shipping:

    - "reference": the jnp einsum implementation above;
    - "flash" / "auto": the Pallas flash kernel with automatic fallback
      to the reference path off-TPU or on unsupported shapes/masks.
    """
    if callable(impl):
        return impl
    if impl == "reference":
        return dot_product_attention
    if impl in ("flash", "auto"):
        # lazy: ops.flash imports this module
        from tensorlink_tpu.ops.flash import flash_attention_impl

        if impl == "flash":
            # explicit choice forces the kernel on every eligible shape;
            # "auto" keeps the measured short-seq einsum win (ops/flash.py
            # MIN_KERNEL_SEQ_AUTO)
            import functools

            return functools.partial(flash_attention_impl, min_kernel_seq=0)
        return flash_attention_impl
    if impl == "ring":
        # sequence-parallel ring attention; valid only inside a shard_map
        # binding the ``seq`` axis (engine Pipeline with mesh seq>1)
        from tensorlink_tpu.parallel.sp import ring_attention_impl

        return ring_attention_impl
    if impl == "ulysses":
        # sequence-parallel all_to_all head/seq swap; same shard_map
        # requirement as "ring", but padding masks are supported
        from tensorlink_tpu.parallel.sp import ulysses_attention_impl

        return ulysses_attention_impl
    raise ValueError(f"unknown attn_impl {impl!r}")


class MultiHeadAttention(Module):
    def __init__(
        self,
        dim: int,
        num_heads: int,
        num_kv_heads: int | None = None,
        head_dim: int | None = None,
        use_bias: bool = True,
        rope: bool = False,
        rope_theta: float = 10000.0,
        causal: bool = False,
        attn_impl: str | Callable = "auto",
        scale: float | None = None,  # None = 1/sqrt(head_dim); T5 = 1.0
        window: int | None = None,  # sliding-window attention (Mistral)
        qkv_fused: bool = False,  # one fused projection (see below)
    ):
        super().__init__()
        self.dim = dim
        self.num_heads = num_heads
        self.num_kv_heads = num_kv_heads or num_heads
        self.head_dim = head_dim or dim // num_heads
        self.use_bias = use_bias
        self.rope = rope
        self.rope_theta = rope_theta
        self.causal = causal
        if window is not None:
            if window < 1:
                raise ValueError(f"window must be >= 1, got {window}")
            # ring/ulysses swallow unknown kwargs (**_) — a window they
            # ignore would SILENTLY widen attention to full context. The
            # reference impl and the flash kernel (in-kernel band mask +
            # whole-block skipping) both honor it.
            resolved = resolve_attn_impl(attn_impl)
            from tensorlink_tpu.ops.flash import flash_attention_impl

            # unwrap partials all the way down (advisor r4: a doubly
            # wrapped partial defeated a single .func hop), and let a
            # user-supplied callable DECLARE window support instead of
            # relying on identity alone
            base = resolved
            while hasattr(base, "func"):
                base = base.func
            declares = getattr(resolved, "supports_window", False) or getattr(
                base, "supports_window", False
            )
            if base not in (dot_product_attention, flash_attention_impl) \
                    and not declares:
                raise ValueError(
                    "sliding-window attention requires attn_impl "
                    "'reference', 'flash', or 'auto' (the ring/ulysses "
                    "kernels do not implement window masking), or a "
                    "callable marked `supports_window = True` that "
                    "honors the window kwarg"
                )
        self.window = window
        if scale is not None:
            # only the reference einsum honors a custom scale; flash/ring
            # would silently use 1/sqrt(D) (T5's no-scale convention is
            # folded into its init, so this matters numerically). Checked
            # on the RESOLVED impl so a callable reference also passes.
            if resolve_attn_impl(attn_impl) is not dot_product_attention:
                raise ValueError(
                    "custom attention scale requires the reference "
                    "attention implementation"
                )
            self.scale = scale
        if isinstance(attn_impl, str):
            # only a string impl is recorded for config()/spec-shipping; a
            # callable can't cross the wire, so the attribute is omitted
            # and a rebuilt module falls back to the "auto" default
            # (review finding: storing None broke module_from_config)
            self.attn_impl = attn_impl
        self._attn = resolve_attn_impl(attn_impl)
        qdim = self.num_heads * self.head_dim
        kvdim = self.num_kv_heads * self.head_dim
        self.qkv_fused = qkv_fused
        if qkv_fused:
            # One matmul instead of three: at decode (T=1, tiny batch)
            # each projection kernel is launch-bound, and fusing q/k/v
            # removed ~2 convolution launches + their bias/reshape
            # fusions per layer per token (measured r5 on v5e — see
            # BASELINE.md decode entry). Layout is Megatron-style
            # PER-KV-GROUP interleave [.., G, (H/G q | 1 k | 1 v), Dh]
            # so a column TP split stays head-aligned whenever the model
            # axis divides num_kv_heads (the same alignment plain GQA TP
            # already requires). Self-attention decoders only: cross
            # attention projects k/v from a different source.
            # fuse_qkv_params converts a q/k/v param tree to this layout.
            if self.num_heads % self.num_kv_heads:
                raise ValueError("qkv_fused requires num_kv_heads | num_heads")
            G = self.num_kv_heads
            hq = self.num_heads // G
            self.child(
                "qkv",
                Dense(dim, G * (hq + 2) * self.head_dim,
                      use_bias=use_bias, shard="col"),
            )
        else:
            self.child("q", Dense(dim, qdim, use_bias=use_bias, shard="col"))
            self.child("k", Dense(dim, kvdim, use_bias=use_bias, shard="col"))
            self.child("v", Dense(dim, kvdim, use_bias=use_bias, shard="col"))
        self.child("o", Dense(qdim, dim, use_bias=use_bias, shard="row"))

    def _project_qkv_fused(self, params, x):
        """Fused projection -> (q [B,T,H,Dh], k/v [B,T,G,Dh])."""
        B, T, _ = x.shape
        G = self.num_kv_heads
        hq = self.num_heads // G
        f = self.children["qkv"].apply(params["qkv"], x)
        f = f.reshape(B, T, G, hq + 2, self.head_dim)
        q = f[:, :, :, :hq].reshape(B, T, self.num_heads, self.head_dim)
        return q, f[:, :, :, hq], f[:, :, :, hq + 1]

    def apply(
        self,
        params,
        x,
        *,
        mask=None,
        cache=None,  # {"k": [B,Tmax,Hkv,D], "v": ..., "index": int32}
        positions=None,
        kv=None,  # cross-attention: keys/values from THIS source (enc out)
        precomputed_kv=None,  # (k, v) [B,Tk,Hkv,D]: skip k/v projections
        bias=None,  # additive attention bias [1|B, H, Tq, Tk] (T5 rel-pos)
        fresh_keys=None,  # None = infer from mask width (see below)
        **kw,
    ):
        B, T, _ = x.shape
        if bias is not None and self._attn is not dot_product_attention:
            # flash/ring/ulysses swallow unknown kwargs (**_) — an
            # additive bias must not be silently dropped
            raise NotImplementedError(
                "additive attention bias requires attn_impl='reference'"
            )
        if self.qkv_fused:
            if kv is not None or precomputed_kv is not None:
                raise NotImplementedError(
                    "qkv_fused projects q/k/v from ONE source — "
                    "cross-attention needs the separate q/k/v layout"
                )
            q, k, v = self._project_qkv_fused(params, x)
        else:
            q = self.children["q"].apply(params["q"], x).reshape(
                B, T, self.num_heads, self.head_dim
            )
            if precomputed_kv is not None:
                # decode-loop cross-attention: the encoder's k/v were
                # projected ONCE via project_kv (rope, if any, must have
                # been applied there — T5 has none)
                k, v = precomputed_kv
            else:
                # one projection path for cached and uncached callers
                k, v = self.project_kv(params, x if kv is None else kv)

        q_offset = 0
        if cache is not None:
            q_offset = cache["index"]
            if positions is None:  # caller-supplied positions win (padded decode)
                if getattr(cache["index"], "ndim", 0) == 1:
                    # per-row index (serving slot form): rows sit at
                    # different (and possibly pad-offset) logical
                    # positions the index alone cannot reconstruct.
                    # Only RoPE consumes positions here — the per-row
                    # attention path itself is mask-authoritative — so
                    # rope-less models (GPT-2: learned positions at the
                    # embedding) may omit them.
                    if self.rope:
                        raise ValueError(
                            "per-row cache indices with rope require "
                            "explicit positions (rows sit at different "
                            "logical positions)"
                        )
                else:
                    positions = cache["index"] + jnp.arange(T)[None, :]
        elif positions is None:
            positions = jnp.arange(T)[None, :]
            if getattr(self, "attn_impl", None) in ("ring", "ulysses"):
                # under sequence sharding T is the LOCAL shard length;
                # RoPE needs global token positions
                positions = positions + jax.lax.axis_index("seq") * T

        if self.rope:
            if precomputed_kv is not None:
                raise NotImplementedError(
                    "precomputed_kv with rope would re-rotate the keys; "
                    "apply rope in project_kv first"
                )
            q = apply_rope(q, positions, self.rope_theta)
            k = apply_rope(k, positions, self.rope_theta)

        new_cache = None
        use_blockwise = False
        if cache is not None and (kv is not None or precomputed_kv is not None):
            raise NotImplementedError(
                "cross-attention KV caching is not supported; precompute "
                "the encoder k/v once (project_kv) and pass them per step "
                "WITHOUT a cache (models/t5.py greedy_decode does)"
            )
        if cache is not None and "block_table" in cache:
            # paged KV cache (parallel/kvpool.py pool + serving block
            # tables): addressing generalizes the per-row slot form from
            # ``slot_base + pos`` to ``block_table[pos // bs] * bs +
            # pos % bs``. Shapes are fully static — the block table is
            # a traced operand, so any request mix reuses one program.
            if bias is not None:
                raise NotImplementedError(
                    "additive attention bias with a paged cache is not "
                    "supported (no cached cross-attention exists to "
                    "need it)"
                )
            out, new_cache = self._apply_paged(params, q, k, v, cache, mask)
            return out, new_cache
        if cache is not None:
            rolling = "rolling" in cache
            # per-row cache indices ([B]-shaped ``index``): the
            # continuous-batching serving form — each batch row is an
            # independent request slot with its own write position
            # (parallel/serving.py). T == 1 decode and T > 1
            # speculative verify-K frontier writes; the caller owns
            # positions and the history validity mask (slot order is
            # logical order per row up to its constant left-pad offset,
            # so causality folds as a per-query slot bound and the
            # positional predicate is never consulted).
            vec_index = getattr(cache["index"], "ndim", 0) == 1
            if vec_index and rolling:
                raise NotImplementedError(
                    "per-row cache indices with a rolling cache would "
                    "need per-row wrap bookkeeping; serve windowed "
                    "models from the monotone cache"
                )
            # rolling (ring-buffer) cache for sliding-window serving:
            # write position wraps modulo capacity, so the cache stays
            # O(window) while generation runs arbitrarily long. The
            # caller owns slot validity/window masking (slot order is
            # no longer logical order past the first wrap) — see
            # parallel/inference.py rolling_cache.
            cap = cache["k"].shape[1]
            if vec_index:
                # one scatter per k/v: token t of row r writes slot
                # index[r] + t. mode="drop" — a row whose region filled
                # to capacity (and any speculative overshoot past it)
                # must write nothing (a clamp would corrupt its last
                # real slot). Retired-but-not-readmitted serving rows
                # park BELOW capacity and do keep writing; that garbage
                # is harmless because the scheduler never validates
                # their slots and prefill grafts the whole region on
                # re-admission. T == 1 is the decode step; T > 1 is the
                # speculative verify-K form (parallel/speculative.py):
                # K+1 candidate tokens advance the decode frontier in
                # ONE weight pass, with per-query causality folded below
                # (query t attends slots <= index+t only), so a rejected
                # suffix never influenced its own prefix and the caller
                # rolls the frontier back by resetting the index —
                # nothing at or below the rolled-back frontier was
                # touched (rollback-safe).
                rows = jnp.arange(B)[:, None]
                wslots = cache["index"][:, None] + jnp.arange(T)[None, :]
                ck = cache["k"].at[rows, wslots].set(
                    k.astype(cache["k"].dtype), mode="drop"
                )
                cv = cache["v"].at[rows, wslots].set(
                    v.astype(cache["v"].dtype), mode="drop"
                )
                new_cache = {"k": ck, "v": cv, "index": cache["index"] + T}
                fresh = False
                if mask is not None and mask.shape[-1] != cap:
                    raise ValueError(
                        "per-row cache indices need a cache-width mask "
                        f"(last dim {cap}), got {mask.shape}"
                    )
                Tk = cap
                k, v = ck, cv
                live_t = wslots + 1  # [B, T] frontier after each query
                kslot = jnp.arange(Tk)[None, None, None, :]
                # per-query causal bound over history + the chunk's own
                # prefix; the caller's mask (validity over history, open
                # at/after the frontier for T > 1) further restricts
                valid = kslot < live_t[:, None, :, None]  # [B, 1, T, Tk]
                mask = valid if mask is None else jnp.logical_and(mask, valid)
                win = getattr(self, "window", None)
                blocks_min = (
                    DECODE_BLOCK if win is not None
                    else DECODE_BLOCKWISE_MIN_WINDOWLESS
                )
                # T > 1 (verify-K) shares the block loop: the K+1
                # queries ride one length-bounded pass instead of
                # paying full cache width (the mask already carries
                # per-query causality)
                use_blockwise = (
                    Tk > blocks_min and Tk % DECODE_BLOCK == 0
                    and bias is None and getattr(self, "scale", None) is None
                )
                if win is not None:
                    # slot-space band == logical band: slot s holds
                    # logical position s - pads with pads constant per
                    # row, so s > live-1-window iff pos > q_pos-window
                    win_start = jnp.maximum(live_t - win, 0)  # [B, T]
                    mask = jnp.logical_and(
                        mask, kslot >= win_start[:, None, :, None]
                    )
                if use_blockwise:
                    out = decode_attention_blockwise(
                        q, k.astype(q.dtype), v.astype(q.dtype),
                        jnp.max(live_t),  # bound: mask owns per-row truth
                        mask=jnp.broadcast_to(
                            mask,
                            jnp.broadcast_shapes(mask.shape, (B, 1, 1, Tk)),
                        ),
                        start=jnp.min(win_start) if win is not None else 0,
                    )
                else:
                    # mask is the sole authority (causality is implied:
                    # every attendable slot is at or before its query)
                    out = self._attn(
                        q, k.astype(q.dtype), v.astype(q.dtype),
                        causal=False, mask=mask, q_offset=0,
                        bias=bias, scale=getattr(self, "scale", None),
                        window=None,
                    )
                out = out.reshape(B, T, self.num_heads * self.head_dim)
                out = self.children["o"].apply(params["o"], out)
                return out, new_cache
            wslot = cache["index"] % cap if rolling else cache["index"]
            if rolling and T > cap:
                # duplicate wrapped slots: scatter order for duplicate
                # indices is implementation-defined — never silent
                raise ValueError(
                    f"rolling write of {T} tokens exceeds ring capacity "
                    f"{cap}: later tokens would overwrite earlier ones "
                    "in undefined order; chunk the write"
                )
            if rolling and T > 1:
                # a multi-token write can CROSS the ring edge (advisor
                # r4: dynamic_update_slice silently CLAMPS there, landing
                # tokens in wrong slots). lax.cond keeps the engine's
                # hot prefill path (index 0, never wraps) on the single
                # contiguous dynamic_update_slice; the wrapping case
                # (chunked-prefill/speculative at index > 0) takes a
                # true modular scatter.
                slots = (wslot + jnp.arange(T)) % cap  # [T]

                def write(c, val):
                    return jax.lax.cond(
                        wslot + T <= cap,
                        lambda cc: jax.lax.dynamic_update_slice_in_dim(
                            cc, val, wslot, axis=1
                        ),
                        lambda cc: cc.at[:, slots].set(val),
                        c,
                    )

                ck = write(cache["k"], k.astype(cache["k"].dtype))
                cv = write(cache["v"], v.astype(cache["v"].dtype))
            else:
                ck = jax.lax.dynamic_update_slice_in_dim(cache["k"], k.astype(cache["k"].dtype), wslot, axis=1)
                cv = jax.lax.dynamic_update_slice_in_dim(cache["v"], v.astype(cache["v"].dtype), wslot, axis=1)
            new_cache = {"k": ck, "v": cv, "index": cache["index"] + T}
            if rolling:
                new_cache["rolling"] = None
            # fresh-keys prefill contract: a multi-token write whose mask
            # covers exactly the T fresh keys attends the JUST-projected
            # k/v, not the (mostly empty) cache — a 4k-prompt prefill
            # into an 8k cache otherwise scores 2x the keys and builds a
            # 2x mask for slots that hold nothing (measured r4: the ring
            # engine's 6.4x serving win over the full cache was mostly
            # this waste). The cache is still written for the decode
            # steps that follow.
            # contract (advisor r4: the inference was mask-shape-only):
            # an EXPLICIT fresh_keys wins; None infers "prefill over an
            # empty cache" from a T-wide mask at T > 1. The inference is
            # unambiguous for the engine (its tight cache capacity is
            # always > T0, so a full-cache mask can't alias a prompt
            # mask). A chunked-prefill/speculative caller at index > 0
            # attends the CACHE and must therefore carry a CACHE-width
            # mask — the non-fresh path masks cache slots, so a T-wide
            # mask cannot express it; fresh_keys=False with a T-wide
            # mask raises here instead of failing in a broadcast deep
            # below (review finding). The index>0 NaN-poison further
            # down still catches silent fresh-path misuse, since the
            # traced index can't gate a branch.
            # A T==1 write carrying a width-1 mask into a WIDER cache is a
            # single-token prompt prefill (the engine's [B,1,1,1] mask at
            # T0==1) and is treated fresh too — ADVICE r5: classifying it
            # non-fresh blessed a width-1 mask that broadcasts over the
            # whole cache, attending unwritten zero-key slots. Decode
            # steps are unaffected: they carry cache-width masks (the
            # valid-slot mask), and a fresh-misclassified caller at
            # index>0 hits the NaN poison below — loud, not silent.
            fresh = (
                fresh_keys if fresh_keys is not None
                else mask is not None and mask.shape[-1] == T
                and (T > 1 or ck.shape[1] > T)
            )
            if fresh and (mask is None or mask.shape[-1] != T):
                raise ValueError(
                    "fresh_keys=True needs a T-wide mask over the "
                    f"just-projected keys (got mask "
                    f"{None if mask is None else mask.shape}, T={T})"
                )
            if (
                not fresh and mask is not None
                and mask.shape[-1] != ck.shape[1]
            ):
                # width-1 masks are NOT accepted here: broadcasting one
                # over the cache would also "validate" every unwritten
                # slot the valid-mask doesn't cover (window bands, pad
                # masks) — a width-1 mask meeting a wider cache is the
                # fresh prefill form, handled above
                raise ValueError(
                    "cache attention needs a cache-width mask (last dim "
                    f"{ck.shape[1]}), got {mask.shape}; a prompt-width "
                    "mask is the fresh-keys prefill form (fresh_keys="
                    "True / the T-wide inference)"
                )
            Tk = ck.shape[1]
            if not fresh:
                k, v = ck, cv
                # mask out cache positions beyond what's been written
                valid = jnp.arange(Tk)[None, None, None, :] < (cache["index"] + T)
                mask = valid if mask is None else jnp.logical_and(mask, valid)
            # single-token decode over a large cache: length-bounded
            # blockwise attention so cost tracks the live prefix, not
            # capacity. The valid mask already enforces causality for the
            # lone query (every slot < live_len is at or before it).
            # Additive biases (T5 rel-pos) and custom scales stay on the
            # full path — the blockwise kernel hardcodes 1/sqrt(D).
            # Thresholds (windowed vs not) below: the fori_loop costs
            # ~12 launch-bound op groups per layer per step, so it must
            # buy real HBM savings. A window skips straight to the band
            # (huge at window << prefix); windowless, the live prefix
            # grows toward capacity and the loop only pays off when the
            # cache is large enough that early-step savings dominate —
            # measured r5 on v5e, a tight 256-slot cache decodes 2x
            # faster on the full einsum than through the loop.
            win = getattr(self, "window", None)
            blocks_min = (
                DECODE_BLOCK if win is not None
                else DECODE_BLOCKWISE_MIN_WINDOWLESS
            )
            use_blockwise = (
                not fresh
                and T == 1 and Tk > blocks_min and Tk % DECODE_BLOCK == 0
                and bias is None and getattr(self, "scale", None) is None
                # rolling: live (index+T) exceeds capacity after the
                # first wrap — the loop's clamped dynamic_slice would
                # visit blocks twice and double-count their slots in the
                # online softmax. Capacity is already window-sized, so
                # the full einsum over it IS the intended cost.
                and not rolling
            )

        window = getattr(self, "window", None)
        if use_blockwise:
            live = cache["index"] + T
            win_start = 0
            if window is not None:
                # the lone query sits at position live-1: it may attend
                # slots (live-1-window, live-1] = [live-window, live)
                win_start = jnp.maximum(live - window, 0)
                kpos = jnp.arange(Tk)[None, None, None, :]
                mask = jnp.logical_and(mask, kpos >= win_start)
            out = decode_attention_blockwise(
                q, k.astype(q.dtype), v.astype(q.dtype),
                live,
                # concrete dims for the in-loop dynamic_slice (a [1,1,1,Tk]
                # broadcastable mask has no sliceable batch dim)
                mask=jnp.broadcast_to(
                    mask, jnp.broadcast_shapes(mask.shape, (B, 1, 1, Tk))
                ),
                start=win_start,
            )
        else:
            if cache is not None and "rolling" in cache:
                # past the first wrap slot order is not position order:
                # slot-space causal/window masking would be wrong. The
                # caller's mask (slot-position bookkeeping) is the sole
                # authority; positional predicates are disabled.
                out = self._attn(
                    q, k.astype(q.dtype), v.astype(q.dtype),
                    causal=False, mask=mask, q_offset=0,
                    bias=bias, scale=getattr(self, "scale", None),
                    window=None,
                )
            else:
                out = self._attn(
                    q, k.astype(q.dtype), v.astype(q.dtype),
                    causal=self.causal, mask=mask, q_offset=q_offset,
                    bias=bias, scale=getattr(self, "scale", None),
                    window=window,
                )
        if cache is not None and fresh:
            # fresh-keys guard: the contract only holds for an EMPTY
            # cache (prefill) — a chunked-prefill/speculative caller at
            # index>0 would silently drop all cached context. The index
            # is traced, so the misuse can't raise at trace time;
            # poisoning the output makes it loud downstream instead
            # (same standard as the LoRA composition guards).
            out = jnp.where(cache["index"] == 0, out, jnp.nan)
        out = out.reshape(B, T, self.num_heads * self.head_dim)
        out = self.children["o"].apply(params["o"], out)
        if cache is not None:
            return out, new_cache
        return out

    def _apply_paged(self, params, q, k, v, cache, mask):
        """Paged-cache attention: scatter the T fresh tokens through the
        per-row block table into the shared block pools, gather each
        row's logical view back, and attend it mask-authoritatively.

        Cache form (parallel/serving.py paged engine):
          ``k``/``v``  [num_blocks, block_size, Hkv, D] — POOLS shared
                       by every row (and owned by the host-side
                       ``BlockPool``);
          ``index``    [B] int32 — each row's logical write position
                       (== its token count: paged rows are never
                       padded);
          ``block_table`` [B, max_blocks] int32 — row r's logical block
                       j lives in pool block ``block_table[r, j]``; the
                       sentinel value ``num_blocks`` marks unmapped
                       entries (writes through them are DROPPED — they
                       must never corrupt another request's block).

        Works for single-token decode (T == 1) AND multi-token chunked
        prefill (T > 1): token t of row r writes pool slot
        ``(bt[r, p // bs], p % bs)`` with ``p = index[r] + t``, and
        queries attend ``kpos <= p`` in the gathered logical view
        (causality in logical coordinates; the window band folds in the
        same way). The caller's mask, when given, must be
        view-width and further restricts (validity); unmapped/garbage
        view slots are harmless because they are never inside
        ``kpos <= index``-coverage of a mapped row.

        int8 pools (``init_paged_cache(quant="int8")`` — detected by
        the ``k_scale`` sibling): fresh k/v quantize at WRITE time
        (``ops/quant.py quantize_kv_int8``, one scale per (token slot,
        kv head)) and dequantize only at READ — inside the Pallas
        kernel per page, or over the gathered view on the XLA path —
        so bf16/f32 KV never materializes at cache width.

        The read side dispatches to the block-table-native Pallas
        kernel (``ops/pallas/paged_decode.py``) when it can engage
        (TPU or ``TL_PAGED_KERNEL=interpret``; ``TL_PAGED_KERNEL=0``
        pins the pure-XLA gather path bit-for-bit).
        """
        B, T = q.shape[0], q.shape[1]
        bt = cache["block_table"]
        idx = cache["index"]
        if getattr(idx, "ndim", 0) != 1:
            raise ValueError(
                f"paged cache needs a per-row [B] index, got ndim "
                f"{getattr(idx, 'ndim', 0)}"
            )
        NB, bs = cache["k"].shape[0], cache["k"].shape[1]
        MB = bt.shape[1]
        Lv = MB * bs  # logical view width
        tpos = idx[:, None] + jnp.arange(T)[None, :]  # [B, T] logical pos
        bslot = tpos // bs
        # rows past their table (parked/retired) force the sentinel so
        # the scatter drops instead of clamping into a real block
        blk = jnp.take_along_axis(bt, jnp.minimum(bslot, MB - 1), axis=1)
        blk = jnp.where(bslot >= MB, NB, blk)
        off = tpos % bs
        quant = "k_scale" in cache
        cks = cvs = None
        if quant:
            from tensorlink_tpu.ops.quant import quantize_kv_int8

            qk, sk = quantize_kv_int8(k)
            qv, sv = quantize_kv_int8(v)
            ck = cache["k"].at[blk, off].set(qk, mode="drop")
            cv = cache["v"].at[blk, off].set(qv, mode="drop")
            cks = cache["k_scale"].at[blk, off].set(sk, mode="drop")
            cvs = cache["v_scale"].at[blk, off].set(sv, mode="drop")
            new_cache = {
                "k": ck, "v": cv, "k_scale": cks, "v_scale": cvs,
                "index": idx + T, "block_table": bt,
            }
        else:
            ck = cache["k"].at[blk, off].set(
                k.astype(cache["k"].dtype), mode="drop"
            )
            cv = cache["v"].at[blk, off].set(
                v.astype(cache["v"].dtype), mode="drop"
            )
            new_cache = {
                "k": ck, "v": cv, "index": idx + T, "block_table": bt,
            }
        if mask is not None and mask.shape[-1] != Lv:
            raise ValueError(
                f"paged cache attention needs a view-width mask "
                f"(last dim {Lv}), got {mask.shape}"
            )
        win = getattr(self, "window", None)
        from tensorlink_tpu.ops.pallas.paged_decode import (
            paged_decode_attention, paged_decode_ok,
        )

        if (
            getattr(self, "scale", None) is None
            and paged_decode_ok(q, ck, mask=mask)
        ):
            # block-table-native kernel: the table lookup runs in the
            # BlockSpec index maps, no logical view ever materializes
            # (and int8 pages dequantize in VMEM)
            out = paged_decode_attention(
                q, ck, cv, bt, idx + T,
                k_scale=cks, v_scale=cvs, mask=mask, window=win,
            )
            out = out.reshape(B, T, self.num_heads * self.head_dim)
            out = self.children["o"].apply(params["o"], out)
            return out, new_cache
        # gather the logical view: [B, MB, bs, Hkv, D] -> [B, Lv, ...].
        # Sentinel table entries clamp into the last pool block — pure
        # garbage, but the positional keep below never reaches them
        # (a mapped row's attendable range is covered by real blocks).
        kk = ck[bt].reshape(B, Lv, *ck.shape[2:])
        vv = cv[bt].reshape(B, Lv, *cv.shape[2:])
        if quant:
            from tensorlink_tpu.ops.quant import dequantize_kv

            kk = dequantize_kv(kk, cks[bt].reshape(B, Lv, -1), q.dtype)
            vv = dequantize_kv(vv, cvs[bt].reshape(B, Lv, -1), q.dtype)
        kpos = jnp.arange(Lv)[None, None, None, :]
        qpos = tpos[:, None, :, None]  # [B, 1, T, 1]
        keep = kpos <= qpos  # causal in logical coordinates
        win_start = None
        if win is not None:
            # block-skip bound from the EARLIEST query (T > 1 verify:
            # later queries' bands start later; the skip must be
            # conservative — per-query band truth stays in ``keep``)
            win_start = jnp.maximum(tpos[:, 0] + 1 - win, 0)  # [B]
            keep = jnp.logical_and(keep, kpos > qpos - win)
        if mask is not None:
            keep = jnp.logical_and(keep, mask)
        blocks_min = (
            DECODE_BLOCK if win is not None
            else DECODE_BLOCKWISE_MIN_WINDOWLESS
        )
        if (
            Lv > blocks_min and Lv % DECODE_BLOCK == 0
            and getattr(self, "scale", None) is None
        ):
            # same length-bounded online-softmax loop as the contiguous
            # per-row path: per-token cost tracks the longest live
            # prefix (mask owns per-row truth)
            out = decode_attention_blockwise(
                q, kk.astype(q.dtype), vv.astype(q.dtype),
                jnp.max(idx) + T,
                mask=jnp.broadcast_to(
                    keep, jnp.broadcast_shapes(keep.shape, (B, 1, 1, Lv))
                ),
                start=jnp.min(win_start) if win is not None else 0,
            )
        else:
            out = self._attn(
                q, kk.astype(q.dtype), vv.astype(q.dtype),
                causal=False, mask=keep, q_offset=0,
                scale=getattr(self, "scale", None), window=None,
            )
        out = out.reshape(B, T, self.num_heads * self.head_dim)
        out = self.children["o"].apply(params["o"], out)
        return out, new_cache

    def project_kv(self, params, src):
        """Project a cross-attention source ONCE: (k, v) [B, Tk, Hkv, D]
        for reuse across a decode loop via ``precomputed_kv=``."""
        if self.qkv_fused:
            raise NotImplementedError(
                "qkv_fused has no standalone k/v projection (build "
                "cross-attention modules with qkv_fused=False)"
            )
        B, Ts, _ = src.shape
        k = self.children["k"].apply(params["k"], src).reshape(
            B, Ts, self.num_kv_heads, self.head_dim
        )
        v = self.children["v"].apply(params["v"], src).reshape(
            B, Ts, self.num_kv_heads, self.head_dim
        )
        return k, v

    def init_cache(self, batch: int, max_len: int, dtype=jnp.bfloat16,
                   rolling: bool = False):
        """``rolling=True`` marks a ring-buffer cache: ``max_len`` is
        then the ring CAPACITY (typically prompt+window, not
        prompt+generation), writes wrap modulo it, and the caller owns
        slot-position masking (parallel/inference.py rolling_cache)."""
        shape = (batch, max_len, self.num_kv_heads, self.head_dim)
        cache = {
            "k": jnp.zeros(shape, dtype),
            "v": jnp.zeros(shape, dtype),
            "index": jnp.zeros((), jnp.int32),
        }
        if rolling:
            # None = empty pytree subtree: the marker is STRUCTURE, not a
            # leaf — a bool leaf would turn into a tracer inside lax.scan
            # carries and break the static `rolling` branch in apply
            cache["rolling"] = None
        return cache

    def init_paged_cache(
        self, num_blocks: int, block_size: int, batch: int,
        max_blocks: int, dtype=jnp.bfloat16,
        quant: str | None = None,
    ):
        """Paged cache form (see ``_apply_paged``): per-layer k/v POOLS
        of ``num_blocks`` fixed-size blocks shared by all ``batch``
        rows, a per-row logical write index, and a per-row block table
        initialized to the ``num_blocks`` sentinel (unmapped — writes
        drop). HBM scales with blocks actually mapped by the host-side
        ``BlockPool``, not ``batch x max_len``.

        ``quant="int8"``: the pools hold int8 with per-(token slot,
        kv head) f32 scales as sibling arrays (``k_scale``/``v_scale``,
        shape ``[num_blocks, block_size, Hkv]``) — ~2x the bf16 pool
        bytes saved at head dims >= 32. ``dtype`` is then ignored for
        k/v. Scales init to 1.0 so unwritten blocks dequantize to exact
        zeros."""
        if quant not in (None, "int8"):
            raise ValueError(f"unknown paged cache quant {quant!r}")
        shape = (num_blocks, block_size, self.num_kv_heads, self.head_dim)
        cache = {
            "index": jnp.zeros((batch,), jnp.int32),
            "block_table": jnp.full(
                (batch, max_blocks), num_blocks, jnp.int32
            ),
        }
        if quant == "int8":
            cache["k"] = jnp.zeros(shape, jnp.int8)
            cache["v"] = jnp.zeros(shape, jnp.int8)
            cache["k_scale"] = jnp.ones(shape[:-1], jnp.float32)
            cache["v_scale"] = jnp.ones(shape[:-1], jnp.float32)
        else:
            cache["k"] = jnp.zeros(shape, dtype)
            cache["v"] = jnp.zeros(shape, dtype)
        return cache
