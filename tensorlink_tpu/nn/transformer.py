"""Transformer building blocks shared by BERT / GPT-2 / ViT / Llama.

A `TransformerStack` is a `Sequential` of homogeneous blocks — which is
exactly what the pipeline partitioner slices into stages (the reference
instead walked arbitrary nn.Module trees and shipped whatever subtree fit,
src/roles/user.py:316-425)."""

from __future__ import annotations

import jax

from tensorlink_tpu.nn.module import Module
from tensorlink_tpu.nn.layers import Dense, Dropout, LayerNorm, RMSNorm
from tensorlink_tpu.nn.attention import MultiHeadAttention


ACTIVATIONS = {
    "gelu": jax.nn.gelu,  # tanh approximation (GPT-2's gelu_new)
    "gelu_exact": lambda x: jax.nn.gelu(x, approximate=False),  # BERT
    "relu": jax.nn.relu,
    "silu": jax.nn.silu,
}


def _decode_glue():
    # lazy: pallas machinery only loads when a decode path actually runs
    from tensorlink_tpu.ops.pallas import decode_glue

    return decode_glue


class FeedForward(Module):
    """MLP block; ``gated=True`` gives the SwiGLU variant (Llama)."""

    def __init__(
        self,
        dim: int,
        hidden_dim: int,
        activation: str = "gelu",
        use_bias: bool = True,
        gated: bool = False,
        dropout: float = 0.0,
    ):
        super().__init__()
        self.dim = dim
        self.hidden_dim = hidden_dim
        self.activation = activation
        self.gated = gated
        self.use_bias = use_bias
        self.dropout = dropout
        self.child("up", Dense(dim, hidden_dim, use_bias=use_bias, shard="col"))
        if gated:
            self.child("gate", Dense(dim, hidden_dim, use_bias=use_bias, shard="col"))
        self.child("down", Dense(hidden_dim, dim, use_bias=use_bias, shard="row"))
        self.child("drop", Dropout(dropout))

    def apply(self, params, x, *, rng=None, train=False, **_):
        act = ACTIVATIONS[self.activation]
        h = self.children["up"].apply(params["up"], x)
        if self.gated:
            h = act(self.children["gate"].apply(params["gate"], x)) * h
        else:
            h = act(h)
        h = self.children["drop"].apply(params["drop"], h, rng=rng, train=train)
        return self.children["down"].apply(params["down"], h)


class TransformerBlock(Module):
    """One attention + MLP block.

    ``norm_style``: "pre" (GPT-2/ViT/Llama) or "post" (BERT).
    ``norm``: "layer" or "rms".
    """

    def __init__(
        self,
        dim: int,
        num_heads: int,
        hidden_dim: int | None = None,
        num_kv_heads: int | None = None,
        norm_style: str = "pre",
        norm: str = "layer",
        norm_eps: float = 1e-6,
        activation: str = "gelu",
        use_bias: bool = True,
        gated_mlp: bool = False,
        causal: bool = False,
        rope: bool = False,
        rope_theta: float = 10000.0,
        dropout: float = 0.0,
        attn_impl: str = "auto",
        moe_experts: int = 0,
        moe_top_k: int = 2,
        moe_capacity_factor: float = 1.25,
        attn_window: int | None = None,  # sliding window (Mistral)
        qkv_fused: bool = False,  # fused q/k/v projection (decode perf)
    ):
        super().__init__()
        self.dim = dim
        self.norm_style = norm_style
        hidden_dim = hidden_dim or 4 * dim
        # constructor args stored for config()/spec-shipping reconstruction
        self.num_heads = num_heads
        self.hidden_dim = hidden_dim
        self.num_kv_heads = num_kv_heads
        self.norm = norm
        self.norm_eps = norm_eps
        self.activation = activation
        self.use_bias = use_bias
        self.gated_mlp = gated_mlp
        self.causal = causal
        self.rope = rope
        self.rope_theta = rope_theta
        self.dropout = dropout
        self.attn_impl = attn_impl
        self.moe_experts = moe_experts
        self.moe_top_k = moe_top_k
        self.moe_capacity_factor = moe_capacity_factor
        self.attn_window = attn_window
        self.qkv_fused = qkv_fused
        norm_cls = RMSNorm if norm == "rms" else LayerNorm
        self.child("norm1", norm_cls(dim, eps=norm_eps))
        self.child("norm2", norm_cls(dim, eps=norm_eps))
        self.child(
            "attn",
            MultiHeadAttention(
                dim,
                num_heads,
                num_kv_heads=num_kv_heads,
                use_bias=use_bias,
                causal=causal,
                rope=rope,
                rope_theta=rope_theta,
                attn_impl=attn_impl,
                window=attn_window,
                qkv_fused=qkv_fused,
            ),
        )
        if moe_experts:
            from tensorlink_tpu.nn.moe import MoEFeedForward

            # the MoE FFN supports neither biases nor internal dropout —
            # fail loudly instead of silently diverging from the dense
            # FeedForward it replaces (review finding)
            if use_bias:
                raise ValueError("moe_experts requires use_bias=False")
            if dropout:
                raise ValueError("moe_experts requires dropout=0")
            self.child(
                "mlp",
                MoEFeedForward(
                    dim,
                    hidden_dim,
                    num_experts=moe_experts,
                    top_k=moe_top_k,
                    capacity_factor=moe_capacity_factor,
                    gated=gated_mlp,
                    activation=activation,
                ),
            )
        else:
            self.child(
                "mlp",
                FeedForward(
                    dim,
                    hidden_dim,
                    activation=activation,
                    use_bias=use_bias,
                    gated=gated_mlp,
                    dropout=dropout,
                ),
            )
        self.child("drop", Dropout(dropout))

    def _mlp(self, mlp_params, h, rng, train):
        """-> (out, aux). Dense FFN has no auxiliary loss."""
        mlp = self.children["mlp"]
        if hasattr(mlp, "apply_with_aux"):
            return mlp.apply_with_aux(mlp_params, h, rng=rng, train=train)
        return mlp.apply(mlp_params, h, rng=rng, train=train), 0.0

    def _run(self, params, x, mask, cache, positions, rng, train):
        attn = self.children["attn"]
        n1, n2 = self.children["norm1"], self.children["norm2"]
        drop = self.children["drop"]
        r1, r2, r3 = (
            jax.random.split(rng, 3) if rng is not None else (None, None, None)
        )

        new_cache = None
        if self.norm_style == "pre":
            h = n1.apply(params["norm1"], x)
            a = attn.apply(params["attn"], h, mask=mask, cache=cache, positions=positions)
            if cache is not None:
                a, new_cache = a
            if (
                cache is not None and not train and x.shape[1] == 1
                and _decode_glue().should_fuse(a, self.norm)
            ):
                # decode fast path: residual add + norm2 in ONE kernel
                # launch (T=1 steps are launch-bound; the add/mean/var/
                # rsqrt/scale chain is otherwise 2 tiny fusions per
                # block per token — see ops/pallas/decode_glue.py)
                x, h = _decode_glue().fused_residual_norm(
                    a, x, params["norm2"]["scale"],
                    params["norm2"].get("bias"),
                    eps=self.norm_eps, kind=self.norm,
                )
            else:
                x = x + drop.apply(params["drop"], a, rng=r1, train=train)
                h = n2.apply(params["norm2"], x)
            m, aux = self._mlp(params["mlp"], h, r2, train)
            x = x + drop.apply(params["drop"], m, rng=r3, train=train)
        else:  # post-LN (BERT)
            a = attn.apply(params["attn"], x, mask=mask, cache=cache, positions=positions)
            if cache is not None:
                a, new_cache = a
            x = n1.apply(params["norm1"], x + drop.apply(params["drop"], a, rng=r1, train=train))
            m, aux = self._mlp(params["mlp"], x, r2, train)
            x = n2.apply(params["norm2"], x + drop.apply(params["drop"], m, rng=r3, train=train))
        return x, new_cache, aux

    def apply(self, params, x, *, mask=None, cache=None, positions=None, rng=None, train=False, **_):
        x, new_cache, _ = self._run(params, x, mask, cache, positions, rng, train)
        if cache is not None:
            return x, new_cache
        return x

    def apply_with_aux(self, params, x, *, mask=None, positions=None, rng=None, train=False, **_):
        """-> (out, aux_loss): the MoE router's load-balancing loss (0 for
        dense blocks). Trainers add ``aux_weight * aux`` to the task loss
        (review finding: plain apply() silently discarded it)."""
        x, _, aux = self._run(params, x, mask, None, positions, rng, train)
        return x, aux

    def router_input(self, params, x, *, mask=None, positions=None):
        """The tensor this block's MLP/router actually sees, per the
        block's OWN norm-style wiring — probes (bench MoE leg, the
        capacity-sweep example) must measure routing stats on this, not
        on a hand-reassembled forward that silently drifts when the
        wiring changes (review finding)."""
        attn = self.children["attn"]
        n1, n2 = self.children["norm1"], self.children["norm2"]
        if self.norm_style == "pre":
            h = n1.apply(params["norm1"], x)
            a = attn.apply(params["attn"], h, mask=mask, positions=positions)
            return n2.apply(params["norm2"], x + a)
        a = attn.apply(params["attn"], x, mask=mask, positions=positions)
        return n1.apply(params["norm1"], x + a)

    def routing_stats(self, params, x, *, mask=None, positions=None) -> dict:
        """MoE router telemetry on the input this block's router sees.
        Raises for dense blocks (no router to probe)."""
        mlp = self.children["mlp"]
        if not hasattr(mlp, "routing_stats"):
            raise ValueError("routing_stats: this block's MLP is dense")
        return mlp.routing_stats(
            params["mlp"], self.router_input(params, x, mask=mask,
                                             positions=positions)
        )


class TransformerStack(Module):
    """N homogeneous blocks. params: {"0": block0, ...}."""

    def __init__(self, num_layers: int, make_block, **block_kw):
        super().__init__()
        self.num_layers = num_layers
        for i in range(num_layers):
            self.child(str(i), make_block(**block_kw))

    def apply(self, params, x, *, mask=None, caches=None, positions=None, rng=None, train=False, **_):
        new_caches = [] if caches is not None else None
        for i in range(self.num_layers):
            r = jax.random.fold_in(rng, i) if rng is not None else None
            blk = self.children[str(i)]
            if caches is not None:
                x, c = blk.apply(
                    params[str(i)], x, mask=mask, cache=caches[i],
                    positions=positions, rng=r, train=train,
                )
                new_caches.append(c)
            else:
                x = blk.apply(
                    params[str(i)], x, mask=mask, positions=positions,
                    rng=r, train=train,
                )
        if caches is not None:
            return x, new_caches
        return x

    def apply_with_aux(self, params, x, *, mask=None, positions=None, rng=None, train=False, **_):
        """-> (out, summed aux losses of all MoE blocks)."""
        aux = 0.0
        for i in range(self.num_layers):
            r = jax.random.fold_in(rng, i) if rng is not None else None
            x, a = self.children[str(i)].apply_with_aux(
                params[str(i)], x, mask=mask, positions=positions,
                rng=r, train=train,
            )
            aux = aux + a
        return x, aux

    def blocks(self) -> list[Module]:
        return [self.children[str(i)] for i in range(self.num_layers)]
