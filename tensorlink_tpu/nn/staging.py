"""Stage-sliced model construction for pipeline-sharded serving.

A :class:`StageSlice` views a contiguous run ``[lo, hi)`` of a decoder's
transformer layers as a standalone compute unit: stage 0 additionally owns
the embedding, the last stage additionally owns the final norm + LM head
(including the tied-embedding head of GPT-2, whose ``wte`` therefore lives
on BOTH ends of the pipeline). The slice never re-implements a layer — it
calls the very same ``TransformerBlock.apply`` the whole-model forward
uses, with the same mask/positions/cache contract, so composing the
stages' outputs reproduces the single-chip forward bit-for-bit (token
parity across the pipeline is an invariant tests pin, not a hope).

Supports the two decoder layouts the repo ships:

- GPT-2 style: ``wte``/``wpe``/``drop``/``blocks``/``ln_f`` + tied head
  (``wte.attend``)
- Llama style: ``tok_emb``/``blocks``/``norm_f``/``lm_head`` (RoPE rides
  ``positions`` into the blocks; no positional embedding table)

``slice_params`` keeps only the subtrees a stage actually needs, which is
what lets a model whose full weights exceed any single worker's HBM run:
each worker holds ~1/N of the block stack plus at most one embedding/head.
"""

from __future__ import annotations

import numpy as np

__all__ = [
    "StageSlice",
    "layer_param_bytes",
    "param_bytes",
    "stage_spans",
]


def param_bytes(tree) -> int:
    """Total bytes of every array leaf in a (nested) param tree."""
    total = 0
    stack = [tree]
    while stack:
        t = stack.pop()
        if isinstance(t, dict):
            stack.extend(t.values())
        elif isinstance(t, (list, tuple)):
            stack.extend(t)
        elif hasattr(t, "nbytes"):
            total += int(t.nbytes)
        elif hasattr(t, "dtype") and hasattr(t, "size"):
            total += int(np.dtype(t.dtype).itemsize) * int(t.size)
    return total


def layer_param_bytes(params: dict) -> list[int]:
    """Per-transformer-layer parameter bytes, in layer order — the load
    vector :func:`stage_spans` partitions against published HBM."""
    blocks = params["blocks"]
    return [param_bytes(blocks[str(i)]) for i in range(len(blocks))]


def stage_spans(loads: list[int] | list[float],
                capacities: list[float]) -> list[tuple[int, int]]:
    """Partition ``len(loads)`` layers into ``len(capacities)`` contiguous
    spans ``[lo, hi)``, one per stage, with per-stage load proportional to
    that stage's capacity (published HBM bytes). Every stage gets at least
    one layer; layers stay contiguous (activations cross the wire once per
    stage boundary, so fragmenting a stage buys nothing and costs hops).
    """
    n, k = len(loads), len(capacities)
    if k <= 0:
        raise ValueError("need at least one stage")
    if n < k:
        raise ValueError(f"{n} layers cannot fill {k} stages")
    if any(c <= 0 for c in capacities):
        raise ValueError("stage capacities must be positive")
    total_cap = float(sum(capacities))
    total_load = float(sum(loads)) or 1.0
    spans: list[tuple[int, int]] = []
    lo, acc = 0, 0.0
    cap_acc = 0.0
    for s in range(k - 1):
        cap_acc += capacities[s]
        target = total_load * (cap_acc / total_cap)
        hi = lo
        while hi < n and (acc + loads[hi] <= target or hi == lo):
            # always take at least one layer; stop once the cumulative
            # load would overshoot this stage's capacity share
            acc += loads[hi]
            hi += 1
        # leave enough layers for the remaining stages
        hi = min(hi, n - (k - 1 - s))
        hi = max(hi, lo + 1)
        spans.append((lo, hi))
        lo = hi
    spans.append((lo, n))
    return spans


class StageSlice:
    """A contiguous layer span of a decoder model, plus (depending on
    position) the embedding front or the norm+head tail."""

    def __init__(self, model, lo: int, hi: int):
        kids = model.children
        if "wte" in kids and "ln_f" in kids:
            self.kind = "gpt2"
        elif "tok_emb" in kids and "norm_f" in kids:
            self.kind = "llama"
        else:
            raise ValueError(
                "StageSlice supports GPT-2-style (wte/wpe/blocks/ln_f) and "
                "Llama-style (tok_emb/blocks/norm_f/lm_head) decoders; got "
                f"children {sorted(kids)}"
            )
        stack = kids["blocks"]
        n = len(stack.children)
        if not (0 <= lo < hi <= n):
            raise ValueError(f"layer span [{lo}, {hi}) invalid for {n} layers")
        self.model = model
        self.lo, self.hi = lo, hi
        self.num_layers = n
        self.first = lo == 0
        self.last = hi == n
        self._blocks = [stack.children[str(i)] for i in range(lo, hi)]

    # ------------------------------------------------------------ params
    def param_keys(self) -> list[str]:
        keys = ["blocks"]
        if self.kind == "gpt2":
            if self.first:
                keys += ["wte", "wpe", "drop"]
            if self.last:
                keys += ["ln_f"]
                if "wte" not in keys:
                    keys.append("wte")  # tied head
        else:
            if self.first:
                keys.append("tok_emb")
            if self.last:
                keys += ["norm_f", "lm_head"]
        return keys

    def slice_params(self, params: dict) -> dict:
        """Keep only this stage's subtrees. The ``blocks`` subtree keeps
        its original layer keys (``str(lo)``..``str(hi-1)``) so a sliced
        tree still addresses layers by their global index."""
        out: dict = {}
        for k in self.param_keys():
            if k == "blocks":
                out["blocks"] = {
                    str(i): params["blocks"][str(i)]
                    for i in range(self.lo, self.hi)
                }
            elif k in params:
                out[k] = params[k]
        return out

    def stage_param_bytes(self, params: dict) -> int:
        return param_bytes(self.slice_params(params))

    # ----------------------------------------------------------- compute
    def embed(self, params, ids, positions):
        """Stage-0 front: token ids -> hidden states. Matches the whole
        model's embedding path exactly (GPT-2 adds wpe then applies the
        inference-identity dropout; Llama embeds only — RoPE is applied
        inside attention from ``positions``)."""
        if not self.first:
            raise ValueError("embed() is a stage-0 operation")
        kids = self.model.children
        if self.kind == "gpt2":
            x = kids["wte"].apply(params["wte"], ids)
            x = x + kids["wpe"].apply(params["wpe"], positions)
            return kids["drop"].apply(params["drop"], x, train=False)
        return kids["tok_emb"].apply(params["tok_emb"], ids)

    def body(self, params, x, caches, *, mask, positions):
        """Run this stage's layers, threading per-layer caches exactly as
        ``TransformerStack.apply`` does. ``caches`` is stage-local (index
        0 == global layer ``lo``); returns ``(x, new_caches)``."""
        new_caches = []
        for j, blk in enumerate(self._blocks):
            gi = str(self.lo + j)
            cache = caches[j] if caches is not None else None
            x, new_attn = blk.apply(
                params["blocks"][gi], x, mask=mask,
                cache=cache, positions=positions,
            )
            new_caches.append(new_attn)
        return x, new_caches

    def head(self, params, x):
        """Last-stage tail: hidden states -> logits (final norm + head)."""
        if not self.last:
            raise ValueError("head() is a last-stage operation")
        kids = self.model.children
        if self.kind == "gpt2":
            x = kids["ln_f"].apply(params["ln_f"], x)
            return kids["wte"].attend(params["wte"], x)
        x = kids["norm_f"].apply(params["norm_f"], x)
        return kids["lm_head"].apply(params["lm_head"], x)

    # ------------------------------------------------------------ caches
    def init_paged_caches(self, num_blocks: int, block_size: int,
                          batch: int, max_blocks: int, *, dtype) -> list:
        """Stage-local paged KV caches — one per layer in ``[lo, hi)``,
        drawn from this stage's own block pool (the whole point: a stage
        holds only its own layers' KV)."""
        return [
            {"attn": blk.children["attn"].init_paged_cache(
                num_blocks, block_size, batch, max_blocks, dtype=dtype)}
            for blk in self._blocks
        ]

    @property
    def hidden_dim(self) -> int:
        cfg = getattr(self.model, "cfg_obj", None) or getattr(
            self.model, "cfg", None)
        return int(cfg.dim)
