from tensorlink_tpu.nn.module import Module, Sequential, init_module  # noqa: F401
from tensorlink_tpu.nn.layers import (  # noqa: F401
    Dense,
    Embedding,
    LayerNorm,
    RMSNorm,
    Dropout,
)
from tensorlink_tpu.nn.attention import MultiHeadAttention, dot_product_attention  # noqa: F401
from tensorlink_tpu.nn.transformer import (  # noqa: F401
    ACTIVATIONS,
    FeedForward,
    TransformerBlock,
    TransformerStack,
)
from tensorlink_tpu.nn.module import (  # noqa: F401
    module_from_config,
    register_activation,
    register_module_type,
)

# Spec-shipping registry: every type here can be rebuilt from config().
for _cls in (
    Dense,
    Embedding,
    LayerNorm,
    RMSNorm,
    Dropout,
    MultiHeadAttention,
    FeedForward,
    TransformerBlock,
):
    register_module_type(_cls)

import jax as _jax  # noqa: E402

for _name, _fn in {
    **ACTIVATIONS,
    "tanh": _jax.numpy.tanh,
    "sigmoid": _jax.nn.sigmoid,
    "flatten": lambda x: x.reshape(x.shape[0], -1),
}.items():
    register_activation(_name, _fn)
