from tensorlink_tpu.nn.module import Module, Sequential, init_module  # noqa: F401
from tensorlink_tpu.nn.layers import (  # noqa: F401
    Dense,
    Embedding,
    LayerNorm,
    RMSNorm,
    Dropout,
)
from tensorlink_tpu.nn.attention import MultiHeadAttention, dot_product_attention  # noqa: F401
from tensorlink_tpu.nn.transformer import (  # noqa: F401
    FeedForward,
    TransformerBlock,
    TransformerStack,
)
