"""Core layers.

Each layer owns its initializer, forward math, and tensor-parallel
PartitionSpec. Compute favors the MXU: Dense keeps a single large matmul;
norms/activations are elementwise (XLA fuses them into neighbors).
"""

from __future__ import annotations

import math

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from tensorlink_tpu.nn.module import Module


def _lecun_normal(key, shape, dtype=jnp.float32, fan_in=None):
    fan_in = fan_in if fan_in is not None else shape[0]
    return jax.random.normal(key, shape, dtype) * math.sqrt(1.0 / fan_in)


def _normal(key, shape, stddev=0.02, dtype=jnp.float32):
    return jax.random.normal(key, shape, dtype) * stddev


class Dense(Module):
    """y = x @ W + b.

    ``shard``: tensor-parallel role —
      - "col": W split on output dim  -> P(None, model_axis)   (Megatron column)
      - "row": W split on input dim   -> P(model_axis, None)   (Megatron row)
      - None:  replicated.
    """

    def __init__(
        self,
        in_dim: int,
        out_dim: int,
        use_bias: bool = True,
        shard: str | None = None,
        init_scheme: str = "lecun",
    ):
        super().__init__()
        self.in_dim = in_dim
        self.out_dim = out_dim
        self.use_bias = use_bias
        self.shard = shard
        self.init_scheme = init_scheme

    def init(self, key):
        wkey, _ = jax.random.split(key)
        if self.init_scheme == "normal":
            w = _normal(wkey, (self.in_dim, self.out_dim))
        else:
            w = _lecun_normal(wkey, (self.in_dim, self.out_dim))
        params = {"w": w}
        if self.use_bias:
            params["b"] = jnp.zeros((self.out_dim,))
        return params

    def param_spec(self, model_axis: str = "model"):
        if self.shard == "col":
            spec = {"w": P(None, model_axis)}
            if self.use_bias:
                spec["b"] = P(model_axis)
        elif self.shard == "row":
            spec = {"w": P(model_axis, None)}
            if self.use_bias:
                spec["b"] = P()
        else:
            spec = {"w": P()}
            if self.use_bias:
                spec["b"] = P()
        return spec

    def apply(self, params, x, **_):
        w = params["w"]
        if isinstance(w, dict):
            # int8 weight-only quantization: int8 matrix + per-channel
            # scale. Dequant fuses into the matmul under XLA; the weight
            # stays int8 in HBM — on memory-bound decode that is the
            # point. (Function-level import: quant walks the module tree
            # and imports Dense.)
            from tensorlink_tpu.ops.quant import dequantize_weight

            w = dequantize_weight(w, x.dtype)
        y = x @ w.astype(x.dtype)
        if "lora_a" in params:
            # LoRA adapters (nn/lora.py): two skinny matmuls on the side,
            # scaled by the tree-carried alpha/rank
            y = y + (
                (x @ params["lora_a"].astype(x.dtype))
                @ params["lora_b"].astype(x.dtype)
            ) * params["lora_s"].astype(x.dtype)
        if self.use_bias:
            y = y + params["b"].astype(x.dtype)
        return y


class Embedding(Module):
    """Token embedding; ``attend`` reuses the table as the LM head
    (weight tying)."""

    def __init__(self, vocab_size: int, dim: int, shard: str | None = None):
        super().__init__()
        self.vocab_size = vocab_size
        self.dim = dim
        self.shard = shard

    def init(self, key):
        return {"table": _normal(key, (self.vocab_size, self.dim))}

    def param_spec(self, model_axis: str = "model"):
        # Vocab-sharded: big table, gather stays local-ish under XLA SPMD.
        return {"table": P(model_axis, None) if self.shard else P()}

    def apply(self, params, ids, **_):
        return params["table"][ids]

    def attend(self, params, x):
        return x @ params["table"].astype(x.dtype).T


class LayerNorm(Module):
    def __init__(self, dim: int, eps: float = 1e-6, use_bias: bool = True):
        super().__init__()
        self.dim = dim
        self.eps = eps
        self.use_bias = use_bias

    def init(self, key):
        p = {"scale": jnp.ones((self.dim,))}
        if self.use_bias:
            p["bias"] = jnp.zeros((self.dim,))
        return p

    def param_spec(self, model_axis: str = "model"):
        p = {"scale": P()}
        if self.use_bias:
            p["bias"] = P()
        return p

    def apply(self, params, x, **_):
        # Normalize in f32 for stability, cast back to compute dtype.
        xf = x.astype(jnp.float32)
        mu = jnp.mean(xf, axis=-1, keepdims=True)
        var = jnp.var(xf, axis=-1, keepdims=True)
        y = (xf - mu) * jax.lax.rsqrt(var + self.eps)
        y = y * params["scale"]
        if self.use_bias:
            y = y + params["bias"]
        return y.astype(x.dtype)


class RMSNorm(Module):
    def __init__(self, dim: int, eps: float = 1e-6):
        super().__init__()
        self.dim = dim
        self.eps = eps

    def init(self, key):
        return {"scale": jnp.ones((self.dim,))}

    def param_spec(self, model_axis: str = "model"):
        return {"scale": P()}

    def apply(self, params, x, **_):
        xf = x.astype(jnp.float32)
        ms = jnp.mean(jnp.square(xf), axis=-1, keepdims=True)
        y = xf * jax.lax.rsqrt(ms + self.eps) * params["scale"]
        return y.astype(x.dtype)


class Dropout(Module):
    """Explicit-rng dropout; no-op unless train=True and rng given."""

    def __init__(self, rate: float):
        super().__init__()
        self.rate = rate

    def init(self, key):
        return {}

    def param_spec(self, model_axis: str = "model"):
        return {}

    def apply(self, params, x, *, rng=None, train: bool = False, **_):
        if not train or self.rate == 0.0 or rng is None:
            return x
        keep = 1.0 - self.rate
        mask = jax.random.bernoulli(rng, keep, x.shape)
        return jnp.where(mask, x / keep, 0.0).astype(x.dtype)
