"""Minimal functional module system.

Design goals (vs the reference, which ships whole pickled ``nn.Module``
objects to workers — src/p2p/torch_node.py:159-162):

- A module is a *description* (hyperparameters only, hashable/serializable);
  parameters are a separate pytree. This is what makes spec-shipping (send
  the description + raw weight arrays, never code) possible, and is the
  natural fit for jax transforms: ``apply`` is a pure function of
  ``(params, inputs)``.
- Every module can report a ``param_spec`` pytree of
  ``jax.sharding.PartitionSpec`` mirroring its params, so tensor-parallel
  placement is declared where the shapes are known instead of being patched
  in afterwards.

API:
    m = Dense(128, 256, shard="col")
    params = m.init(jax.random.key(0))
    y = m.apply(params, x)
    specs = m.param_spec(model_axis="model")
"""

from __future__ import annotations

from typing import Any, Callable, Mapping, Sequence

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P


class Module:
    """Base class. Subclasses set hyperparams in __init__ and implement
    ``init(key) -> params`` and ``apply(params, *args, **kw)``.

    Composite modules register children with ``self.child(name, module)``;
    ``init``/``param_spec`` then recurse automatically for registered
    children (a subclass may still override to add its own leaves).
    """

    def __init__(self) -> None:
        self._children: dict[str, "Module"] = {}

    # -- composition ----------------------------------------------------
    def child(self, name: str, module: "Module") -> "Module":
        self._children[name] = module
        return module

    @property
    def children(self) -> Mapping[str, "Module"]:
        return self._children

    # -- parameters -----------------------------------------------------
    def init(self, key: jax.Array) -> dict[str, Any]:
        """Default: recurse into children."""
        params: dict[str, Any] = {}
        keys = jax.random.split(key, max(len(self._children), 1))
        for k, (name, mod) in zip(keys, self._children.items()):
            params[name] = mod.init(k)
        return params

    def apply(self, params, *args, **kwargs):
        raise NotImplementedError

    def __call__(self, params, *args, **kwargs):
        return self.apply(params, *args, **kwargs)

    # -- sharding -------------------------------------------------------
    def param_spec(self, model_axis: str = "model") -> dict[str, Any]:
        """PartitionSpec pytree mirroring ``init``'s output. Default:
        children recurse; leaf modules override."""
        return {
            name: mod.param_spec(model_axis) for name, mod in self._children.items()
        }

    # -- introspection --------------------------------------------------
    def config(self) -> dict[str, Any]:
        """Serializable hyperparameter description (for spec shipping)."""
        out = {
            k: v
            for k, v in self.__dict__.items()
            if not k.startswith("_") and isinstance(v, (int, float, str, bool, tuple, type(None)))
        }
        out["__type__"] = type(self).__name__
        if self._children:
            out["__children__"] = {n: m.config() for n, m in self._children.items()}
        return out


class Sequential(Module):
    """Chain of modules; params keyed "0", "1", ... Stage partitioning for
    pipeline parallelism slices this list (the TPU-native analogue of the
    reference's module-tree walk in src/ml/distributed.py:305-378)."""

    def __init__(self, layers: Sequence[Module]):
        super().__init__()
        self.layers = list(layers)
        for i, l in enumerate(self.layers):
            self.child(str(i), l)

    def apply(self, params, x, **kwargs):
        rng = kwargs.pop("rng", None)
        for i, layer in enumerate(self.layers):
            # per-layer rng fold (same scheme as TransformerStack):
            # passing one key to every layer would draw bitwise-identical
            # dropout masks in each of them
            r = None if rng is None else jax.random.fold_in(rng, i)
            x = layer.apply(params[str(i)], x, rng=r, **kwargs)
        return x

    def __getitem__(self, idx) -> Module | "Sequential":
        if isinstance(idx, slice):
            return Sequential(self.layers[idx])
        return self.layers[idx]

    def __len__(self) -> int:
        return len(self.layers)


class Lambda(Module):
    """Stateless function as a module (activations, reshapes)."""

    def __init__(self, fn: Callable, name: str = "lambda"):
        super().__init__()
        self.name = name
        self._fn = fn

    def init(self, key):
        return {}

    def param_spec(self, model_axis: str = "model"):
        return {}

    def apply(self, params, x, **kwargs):
        return self._fn(x)


class Parallel(Module):
    """N branches over the same input, combined elementwise or by
    feature concat — the native container for BRANCHING architectures
    (reference parse_model walks arbitrary nn.Module trees,
    src/roles/user.py:316-425; our equivalent is partition_tree in
    roles/user.py, which linearizes this container into a placeable
    chain of carry-packed stages). params: {"0": ..., "N-1": ...}."""

    COMBINES = ("add", "mul", "concat")

    def __init__(self, branches: Sequence[Module], combine: str = "add"):
        super().__init__()
        if combine not in self.COMBINES:
            raise ValueError(f"combine must be one of {self.COMBINES}")
        self.combine = combine
        self.branches = list(branches)
        for i, b in enumerate(self.branches):
            self.child(str(i), b)

    def apply(self, params, x, **kwargs):
        rng = kwargs.pop("rng", None)
        outs = []
        for i, b in enumerate(self.branches):
            r = None if rng is None else jax.random.fold_in(rng, i)
            outs.append(b.apply(params[str(i)], x, rng=r, **kwargs))
        if self.combine == "concat":
            return jnp.concatenate(outs, axis=-1)
        acc = outs[0]
        for o in outs[1:]:
            acc = acc + o if self.combine == "add" else acc * o
        return acc


class AppendTail(Module):
    """z -> concat([z, z[..., :width]], -1): re-append the carried input
    x at the tail so the NEXT branch's chain can consume it. Part of
    partition_tree's carry packing (see Parallel)."""

    def __init__(self, width: int):
        super().__init__()
        self.width = width

    def init(self, key):
        return {}

    def param_spec(self, model_axis: str = "model"):
        return {}

    def apply(self, params, z, **kwargs):
        return jnp.concatenate([z, z[..., : self.width]], axis=-1)


class TailMap(Module):
    """z = [prefix | h] -> [prefix | inner(h)]: run one chain unit on
    the tail segment while carrying the prefix (the original input plus
    already-computed branch outputs) through the stage boundary.
    params: {"inner": ...}."""

    def __init__(self, inner: Module, head_width: int):
        super().__init__()
        self.head_width = head_width
        self.child("inner", inner)

    def apply(self, params, z, **kwargs):
        head = z[..., : self.head_width]
        h = self.children["inner"].apply(
            params["inner"], z[..., self.head_width :], **kwargs
        )
        return jnp.concatenate([head, h], axis=-1)


class CombineTail(Module):
    """z = [x | a_1 .. a_n] -> combine(a_i): drop the carried input and
    merge the branch outputs (Parallel.combine semantics)."""

    def __init__(self, combine: str, x_width: int, widths: Sequence[int]):
        super().__init__()
        if combine not in Parallel.COMBINES:
            raise ValueError(f"combine must be one of {Parallel.COMBINES}")
        self.combine = combine
        self.x_width = x_width
        self.widths = tuple(widths)

    def init(self, key):
        return {}

    def param_spec(self, model_axis: str = "model"):
        return {}

    def apply(self, params, z, **kwargs):
        outs = []
        off = self.x_width
        for w in self.widths:
            outs.append(z[..., off : off + w])
            off += w
        if self.combine == "concat":
            return jnp.concatenate(outs, axis=-1)
        acc = outs[0]
        for o in outs[1:]:
            acc = acc + o if self.combine == "add" else acc * o
        return acc


# ----------------------------------------------------------------- specs
# Module reconstruction from config() dicts — the receiving end of
# spec-shipping. The sender transmits `module.config()` (plain data) +
# weights; the receiver rebuilds the module tree locally and jit-compiles.
# Code never crosses the wire (contrast: reference pickles whole
# nn.Modules, src/p2p/torch_node.py:159-162).

MODULE_TYPES: dict[str, type] = {}

_ACTIVATION_FNS: dict[str, Callable] = {}


def register_module_type(cls: type) -> type:
    MODULE_TYPES[cls.__name__] = cls
    return cls


def register_activation(name: str, fn: Callable) -> None:
    _ACTIVATION_FNS[name] = fn


# carry-packing wrappers are defined above the registry (class order
# follows the dataflow story); registered here
for _cls in (AppendTail, CombineTail):
    register_module_type(_cls)
del _cls


def module_from_config(cfg: Mapping[str, Any]) -> Module:
    """Rebuild a module from its config() dict. Composite modules that
    construct their own children in __init__ are rebuilt by constructor
    args; Sequential rebuilds children recursively; Lambda maps back to a
    registered activation by name."""
    import inspect

    t = cfg.get("__type__")
    if t == "Sequential":
        children = cfg.get("__children__", {})
        order = sorted(children, key=int)
        return Sequential([module_from_config(children[i]) for i in order])
    if t == "Parallel":
        children = cfg.get("__children__", {})
        order = sorted(children, key=int)
        return Parallel(
            [module_from_config(children[i]) for i in order],
            combine=cfg.get("combine", "add"),
        )
    if t == "TailMap":
        return TailMap(
            module_from_config(cfg["__children__"]["inner"]),
            head_width=cfg["head_width"],
        )
    if t == "Lambda":
        name = cfg.get("name", "")
        if name not in _ACTIVATION_FNS:
            raise ValueError(f"unknown activation {name!r}")
        return Lambda(_ACTIVATION_FNS[name], name=name)
    cls = MODULE_TYPES.get(t)
    if cls is None:
        raise ValueError(f"unknown module type {t!r}")
    sig = inspect.signature(cls.__init__)
    kwargs = {k: cfg[k] for k in sig.parameters if k != "self" and k in cfg}
    # json round-trips tuples to lists; coerce back where needed
    return cls(**kwargs)


def init_module(module: Module, key: jax.Array, dtype=jnp.float32):
    """Init + optional cast of floating leaves."""
    params = module.init(key)
    if dtype != jnp.float32:
        params = jax.tree.map(
            lambda x: x.astype(dtype)
            if jnp.issubdtype(x.dtype, jnp.floating)
            else x,
            params,
        )
    return params


def spec_tree_to_shardings(spec_tree, mesh):
    """PartitionSpec pytree -> NamedSharding pytree."""
    from jax.sharding import NamedSharding

    return jax.tree.map(
        lambda s: NamedSharding(mesh, s),
        spec_tree,
        is_leaf=lambda x: isinstance(x, P),
    )
