"""LoRA: low-rank adapters for parameter-efficient fine-tuning.

The reference's whole purpose is fine-tuning models too big for one
machine; LoRA shrinks that job — train two rank-r matrices per targeted
projection instead of the full weight, cutting trainable params by
orders of magnitude.

Design (TPU-first):
- adapters live INSIDE the Dense param dict ({"w", "lora_a", "lora_b"}),
  so the stacked-stage engine, spec shipping, and checkpointing all see
  one ordinary pytree — no parallel adapter registry;
- Dense.apply adds ``(x @ a) @ b * (alpha/rank)`` when adapters are
  present: two skinny matmuls, MXU-fine, fused by XLA;
- freezing is ``mask_to_lora`` applied by both trainers to the GRADS
  (before clipping/optimizer, so frozen params neither dominate the
  clip norm nor accumulate moments) and to the final updates (AdamW's
  decoupled weight decay moves params even at zero grad) — simple and
  schedule-agnostic (GPipe and 1F1B unchanged). Moment buffers are
  still allocated for frozen params (sharded; a masked-optimizer
  variant could reclaim them later) — the big wins here are the tiny
  gradient math and the tiny checkpoint/update deltas;
- ``lora_merge`` folds the adapters back into ``w`` for serving at
  exactly base-model cost (and composes with int8 quantization).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

LORA_KEYS = ("lora_a", "lora_b")  # trainable adapter leaves
LORA_ALL = LORA_KEYS + ("lora_s",)


def lora_init(
    module,
    params,
    key,
    *,
    rank: int = 8,
    alpha: float = 16.0,
    targets: tuple | None = ("q", "k", "v", "o", "up", "gate", "down"),
    _name: str = "",
):
    """Add {lora_a, lora_b} to every Dense child whose NAME is in
    ``targets`` (attention projections and/or MLP, per convention;
    None = every Dense — e.g. a plain Sequential whose children are
    named by index). ``a`` is small-normal, ``b`` zeros — the adapted
    model starts exactly at the base model. Returns a NEW param tree."""
    from tensorlink_tpu.nn.layers import Dense, _normal

    if isinstance(module, Dense):
        if (targets is None or _name in targets) and "w" in params:
            ka, _ = jax.random.split(key)
            w = params["w"]
            return {
                **params,
                # both adapter halves follow the BASE weight's dtype —
                # mixed a/b dtypes would skew checkpoint bytes and
                # moment dtypes between the pair
                "lora_a": _normal(
                    ka, (w.shape[0], rank), stddev=0.01
                ).astype(w.dtype),
                "lora_b": jnp.zeros((rank, w.shape[1]), w.dtype),
                # self-describing scale: the tree (not module attrs)
                # carries alpha/rank, so spec-shipping and merge need no
                # side-channel configuration
                "lora_s": jnp.float32(alpha / rank),
            }
        return params
    out = dict(params) if isinstance(params, dict) else params
    for name, child in getattr(module, "children", {}).items():
        if isinstance(params, dict) and name in params:
            key, sub = jax.random.split(key)
            out[name] = lora_init(
                child, params[name], sub, rank=rank, alpha=alpha,
                targets=targets, _name=name,
            )
    return out


def lora_scale(rank: int, alpha: float) -> float:
    return alpha / rank


def lora_merge(module, params):
    """Fold adapters into the base weights: w += a @ b * lora_s,
    dropping the adapter leaves — serving then costs exactly the base
    model (and the merged tree quantizes like any other)."""
    from tensorlink_tpu.nn.layers import Dense

    if isinstance(module, Dense):
        if "lora_a" in params:
            delta = (
                params["lora_a"].astype(jnp.float32)
                @ params["lora_b"].astype(jnp.float32)
            ) * params["lora_s"]
            merged = {
                k: v for k, v in params.items() if k not in LORA_ALL
            }
            merged["w"] = (
                params["w"].astype(jnp.float32) + delta
            ).astype(params["w"].dtype)
            return merged
        return params
    out = dict(params) if isinstance(params, dict) else params
    for name, child in getattr(module, "children", {}).items():
        if isinstance(params, dict) and name in params:
            out[name] = lora_merge(child, params[name])
    return out


def lora_spec_tree(spec_tree, params):
    """Patch a PartitionSpec tree for a LoRA'd param tree (structural,
    like ops/quant.quantized_spec_tree): where params carry adapters,
    derive their specs from the base weight's — ``a`` shards its in-dim
    like w's rows, ``b`` its out-dim like w's columns, the scale
    replicates. Works for any nesting (engine patches per-layer specs
    before stacking)."""
    from jax.sharding import PartitionSpec as P

    def walk(spec, leaf):
        if isinstance(leaf, dict) and "lora_a" in leaf and "w" in leaf:
            wspec = spec["w"]
            row = wspec[0] if isinstance(wspec, P) and len(wspec) > 0 else None
            col = wspec[1] if isinstance(wspec, P) and len(wspec) > 1 else None
            return {
                **spec,
                "lora_a": P(row, None),
                "lora_b": P(None, col),
                "lora_s": P(),
            }
        if isinstance(leaf, dict):
            return {
                k: (walk(spec[k], leaf[k]) if k in spec else spec.get(k))
                for k in leaf
            } if isinstance(spec, dict) else spec
        return spec

    return walk(spec_tree, params)


def mask_to_lora(updates):
    """Zero every update that is not an adapter leaf: base weights (and
    the scale) freeze while riding the SAME sharded optimizer program —
    schedule-agnostic (GPipe/1F1B/DP/TP unchanged)."""
    def mask(path, u):
        trainable = any(
            getattr(k, "key", None) in LORA_KEYS for k in path
        )
        return u if trainable else jnp.zeros_like(u)

    return jax.tree_util.tree_map_with_path(mask, updates)


def trainable_leaf_count(params) -> tuple[int, int]:
    """(lora trainable, total) parameter counts — the brag numbers."""
    import numpy as np

    total = lora = 0
    for path, leaf in jax.tree_util.tree_flatten_with_path(params)[0]:
        n = int(np.prod(jnp.asarray(leaf).shape))
        total += n
        if any(
            getattr(k, "key", None) in LORA_KEYS for k in path
        ):
            lora += n
    return lora, total
