"""Pure-Python Keccak-256 (the pre-FIPS Ethereum variant, 0x01 padding).

Ethereum function selectors and event topics use original Keccak-256, not
NIST SHA3-256 (different domain-separation byte: 0x01 vs 0x06), so
`hashlib.sha3_256` cannot be used. The reference gets this via web3.py's
bundled eth-hash; this environment has no keccak provider, so the
permutation is implemented directly from the public Keccak specification.
Throughput is irrelevant here: the only inputs are 4-byte selectors'
signatures and small registration payloads on the control plane.
"""

from __future__ import annotations

_MASK = (1 << 64) - 1

_ROUND_CONSTANTS = [
    0x0000000000000001, 0x0000000000008082, 0x800000000000808A,
    0x8000000080008000, 0x000000000000808B, 0x0000000080000001,
    0x8000000080008081, 0x8000000000008009, 0x000000000000008A,
    0x0000000000000088, 0x0000000080008009, 0x000000008000000A,
    0x000000008000808B, 0x800000000000008B, 0x8000000000008089,
    0x8000000000008003, 0x8000000000008002, 0x8000000000000080,
    0x000000000000800A, 0x800000008000000A, 0x8000000080008081,
    0x8000000000008080, 0x0000000080000001, 0x8000000080008008,
]

# rho rotation offsets, indexed [x][y]
_ROTATIONS = [
    [0, 36, 3, 41, 18],
    [1, 44, 10, 45, 2],
    [62, 6, 43, 15, 61],
    [28, 55, 25, 21, 56],
    [27, 20, 39, 8, 14],
]

_RATE_BYTES = 136  # 1600 - 2*256 bits


def _rotl(v: int, n: int) -> int:
    return ((v << n) | (v >> (64 - n))) & _MASK


def _keccak_f(state: list[list[int]]) -> list[list[int]]:
    a = state
    for rc in _ROUND_CONSTANTS:
        # theta
        c = [a[x][0] ^ a[x][1] ^ a[x][2] ^ a[x][3] ^ a[x][4] for x in range(5)]
        d = [c[(x - 1) % 5] ^ _rotl(c[(x + 1) % 5], 1) for x in range(5)]
        a = [[a[x][y] ^ d[x] for y in range(5)] for x in range(5)]
        # rho + pi
        b = [[0] * 5 for _ in range(5)]
        for x in range(5):
            for y in range(5):
                b[y][(2 * x + 3 * y) % 5] = _rotl(a[x][y], _ROTATIONS[x][y])
        # chi
        a = [
            [b[x][y] ^ ((~b[(x + 1) % 5][y]) & _MASK & b[(x + 2) % 5][y])
             for y in range(5)]
            for x in range(5)
        ]
        # iota
        a[0][0] ^= rc
    return a


def keccak256(data: bytes) -> bytes:
    # multi-rate padding with the Keccak (not SHA3) domain byte
    pad_len = _RATE_BYTES - (len(data) % _RATE_BYTES)
    padded = bytearray(data)
    padded += b"\x00" * pad_len
    padded[len(data)] ^= 0x01
    padded[-1] ^= 0x80

    state = [[0] * 5 for _ in range(5)]
    for off in range(0, len(padded), _RATE_BYTES):
        block = padded[off:off + _RATE_BYTES]
        for i in range(_RATE_BYTES // 8):
            lane = int.from_bytes(block[8 * i:8 * i + 8], "little")
            state[i % 5][i // 5] ^= lane
        state = _keccak_f(state)

    out = bytearray()
    for i in range(4):  # 32 bytes = 4 lanes, all within the first plane
        out += state[i % 5][i // 5].to_bytes(8, "little")
    return bytes(out)


def selector(signature: str) -> bytes:
    """4-byte Solidity function selector, e.g. selector('transfer(address,uint256)')."""
    return keccak256(signature.encode("ascii"))[:4]
