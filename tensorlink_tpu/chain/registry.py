"""Chain-backed validator registry.

Implements the `roles.registry.Registry` seam against an EVM contract, the
TPU-native analogue of the reference's web3 binding (src/p2p/smart_node.py:
165-179 contract init; 522-537 getValidatorCount/getValidatorInfo; 357-379
handshake role verification). Node code is oblivious to the backing store:
hermetic tests use InMemoryRegistry, deployments pass a Web3Registry.

Contract interface (minimal, defined by this framework — the reference's
1.5 MB generated ABI is mostly unused surface):

    function validatorCount() view returns (uint256)
    function validatorAt(uint256 i) view returns
        (string nodeId, string host, uint256 port,
         uint256 reputationMilli, uint256 registeredAt)
    function isValidator(string nodeId) view returns (bool)
    function registerValidator(string nodeId, string host, uint256 port)
    function deregisterValidator(string nodeId)
    function setReputation(string nodeId, uint256 reputationMilli)
    function jobCount() view returns (uint256)
    function requestJob(string userId, uint256 capacityBytes,
                        uint256 paymentMilli) returns (uint256 jobId)
    function completeJob(uint256 jobId)
    function jobAt(uint256 jobId) view returns
        (string userId, uint256 capacityBytes, uint256 paymentMilli,
         bool completed)

Reputation and payment ride as milli-units (uint) since the EVM has no
floats. The job functions are the ON-CHAIN job/payment records the
reference only carried as commented-out intent (src/roles/user.py:
50-64, 171-199; the whitepaper anchors payments on-chain) — here the
write path is live: UserNode.request_job(chain_registry=...) records
the request before placement and DistributedJob.complete_onchain()
closes it.
"""

from __future__ import annotations

import time

from tensorlink_tpu.chain import abi
from tensorlink_tpu.chain.keccak import keccak256, selector
from tensorlink_tpu.chain.rpc import ChainError, ChainRpc
from tensorlink_tpu.p2p.dht import PeerInfo
from tensorlink_tpu.roles.registry import Registry, ValidatorEntry

_VALIDATOR_AT_RETURNS = ["string", "string", "uint256", "uint256", "uint256"]

_SEL = {
    "validatorCount": selector("validatorCount()"),
    "validatorAt": selector("validatorAt(uint256)"),
    "isValidator": selector("isValidator(string)"),
    "registerValidator": selector("registerValidator(string,string,uint256)"),
    "deregisterValidator": selector("deregisterValidator(string)"),
    "setReputation": selector("setReputation(string,uint256)"),
    "jobCount": selector("jobCount()"),
    "requestJob": selector("requestJob(string,uint256,uint256)"),
    "completeJob": selector("completeJob(uint256)"),
    "jobAt": selector("jobAt(uint256)"),
}


class Web3Registry(Registry):
    """Registry reads via `eth_call`, writes via node-managed transactions.

    `cache_ttl` bounds RPC traffic from the hot handshake path: the
    reference issues one `eth_call` per inbound validator handshake
    (smart_node.py:357-373); here verification hits a TTL-cached local
    view and only misses go to the chain.
    """

    def __init__(
        self,
        rpc_url: str,
        contract_address: str,
        sender: str | None = None,
        cache_ttl: float = 5.0,
        rpc: ChainRpc | None = None,
    ):
        self.rpc = rpc or ChainRpc(rpc_url)
        self.contract = contract_address
        self.sender = sender
        self.cache_ttl = cache_ttl
        self._cache: list[ValidatorEntry] | None = None
        self._cache_at = 0.0

    # ------------------------------------------------------------ raw calls
    def _call(self, name: str, types: list[str], values: list) -> bytes:
        out = self.rpc.eth_call(
            self.contract, _SEL[name] + abi.encode(types, values)
        )
        if not out:
            # every read in this interface declares return values; empty
            # returndata means calling an address with no contract code —
            # surface the misconfiguration instead of decoding zeros
            raise ChainError(
                f"{name}: empty returndata from {self.contract} — wrong "
                "contract address or contract not deployed on this chain?"
            )
        return out

    def _read(self, name: str, ret_types: list[str], types: list[str],
              values: list) -> list:
        """eth_call + decode; decode failures (truncated/garbage
        returndata from a wrong contract) surface as ChainError so both
        symptoms of a misconfigured address share one exception type."""
        out = self._call(name, types, values)
        try:
            return abi.decode(ret_types, out)
        except ValueError as e:
            raise ChainError(
                f"{name}: undecodable returndata from {self.contract}: {e}"
            ) from e

    def _transact(self, name: str, types: list[str], values: list) -> str:
        # mark the cached view stale (next read refetches) but KEEP it for
        # is_validator_local — nulling it would fail-close the event-loop
        # gate for the whole window until the next refresh
        self._cache_at = 0.0
        return self.rpc.send_transaction(
            self.contract, _SEL[name] + abi.encode(types, values), sender=self.sender
        )

    # ------------------------------------------------------------- Registry
    def register_validator(self, info: PeerInfo) -> None:
        self._transact(
            "registerValidator",
            ["string", "string", "uint256"],
            [info.node_id, info.host, info.port],
        )

    def deregister_validator(self, node_id: str) -> None:
        self._transact("deregisterValidator", ["string"], [node_id])

    def validator_count(self) -> int:
        [count] = self._read("validatorCount", ["uint256"], [], [])
        return count

    def list_validators(self) -> list[ValidatorEntry]:
        now = time.monotonic()
        if self._cache is not None and now - self._cache_at < self.cache_ttl:
            return list(self._cache)
        entries = []
        for i in range(self.validator_count()):
            node_id, host, port, rep_milli, registered_at = self._read(
                "validatorAt", _VALIDATOR_AT_RETURNS, ["uint256"], [i]
            )
            entries.append(
                ValidatorEntry(
                    info=PeerInfo(node_id=node_id, role="validator",
                                  host=host, port=port),
                    reputation=rep_milli / 1000.0,
                    registered_at=float(registered_at),
                )
            )
        self._cache, self._cache_at = entries, now
        return list(entries)

    def is_validator(self, node_id: str) -> bool:
        cached = self._cache
        if cached is not None and time.monotonic() - self._cache_at < self.cache_ttl:
            if any(e.info.node_id == node_id for e in cached):
                return True
        [ok] = self._read("isValidator", ["bool"], ["string"], [node_id])
        return ok

    def is_validator_local(self, node_id: str) -> bool:
        """Cache-only check for event-loop call sites: never an RPC, stale
        allowed (the validator refreshes the view periodically). A miss on
        an empty cache denies — fail-closed until the first refresh."""
        cached = self._cache or []
        return any(e.info.node_id == node_id for e in cached)

    def refresh(self) -> None:
        # stale-while-revalidate: the old view keeps serving
        # is_validator_local during the N+1 RPC roundtrips; list_validators
        # swaps the fresh list in atomically at the end
        self._cache_at = 0.0
        self.list_validators()

    def set_reputation(self, node_id: str, rep: float) -> None:
        self._transact(
            "setReputation", ["string", "uint256"],
            [node_id, max(0, round(rep * 1000))],
        )

    # -- on-chain job/payment records (module docstring) ----------------

    # keccak256("JobRequested(uint256,string)") — topic[0] of the event the
    # contract emits per requestJob; topic[1] is the indexed job id
    JOB_REQUESTED_TOPIC = "0x" + keccak256(
        b"JobRequested(uint256,string)"
    ).hex()

    def request_job_onchain(
        self, user_id: str, capacity_bytes: int, payment_milli: int
    ) -> int:
        """Record a job request; -> its on-chain job id. A transaction
        cannot return a value over JSON-RPC, so the id comes from the
        JobRequested event in the transaction's receipt logs — race-free
        under concurrent submitters (each receipt names ITS job). Only
        when the node returns no receipt/logs (old contract without the
        event) does this fall back to re-reading jobCount(), which is
        correct only while a single user submits at a time — the
        constraint UserNode.request_job documents."""
        tx_hash = self._transact(
            "requestJob", ["string", "uint256", "uint256"],
            [user_id, int(capacity_bytes), int(payment_milli)],
        )
        try:
            receipt = self.rpc.get_transaction_receipt(tx_hash)
        except ChainError:
            receipt = None
        status = (receipt or {}).get("status")
        if status is not None and int(status, 16) == 0:
            # reverted: falling through to the jobCount() fallback here
            # would return some OTHER job's id as if this request
            # succeeded — and its escrow would later be completed
            raise ChainError(
                f"requestJob transaction {tx_hash} reverted (status 0x0)"
            )
        for log in (receipt or {}).get("logs", []):
            topics = log.get("topics") or []
            if len(topics) >= 2 and topics[0] == self.JOB_REQUESTED_TOPIC:
                return int(topics[1], 16)
        # legacy fallback: jobCount() after the receipt (single-submitter
        # window only — see docstring)
        [count] = self._read("jobCount", ["uint256"], [], [])
        return int(count)

    def complete_job_onchain(self, job_id: int) -> None:
        self._transact("completeJob", ["uint256"], [int(job_id)])

    def job_onchain(self, job_id: int) -> dict:
        user_id, cap, pay, done = self._read(
            "jobAt", ["string", "uint256", "uint256", "bool"],
            ["uint256"], [int(job_id)],
        )
        return {
            "user_id": user_id, "capacity_bytes": int(cap),
            "payment_milli": int(pay), "completed": bool(done),
        }
