"""EVM chain integration, dependency-free.

The reference binds an EVM smart contract through web3.py for validator
enumeration, handshake role verification, and (planned) reputation and
payments (reference src/p2p/smart_node.py:165-179,522-537, config/
SmartNodes.json ABI). This package provides the same capability with zero
third-party dependencies: a pure-Python keccak-256, a minimal Solidity ABI
codec, a stdlib JSON-RPC client, and `Web3Registry` — a chain-backed
implementation of the `roles.registry.Registry` seam. `mock.MockChainServer`
is the hermetic stand-in for tests and off-chain development (the analogue of
the reference's `off_chain_test=True` bypass).
"""

from tensorlink_tpu.chain.registry import Web3Registry  # noqa: F401
from tensorlink_tpu.chain.rpc import ChainError, ChainRpc  # noqa: F401
