"""Stdlib JSON-RPC client for EVM endpoints.

Covers exactly what the registry needs — `eth_call` reads and
`eth_sendTransaction` writes against a node-managed account — the same
read-mostly surface the reference exercises through web3.py
(src/p2p/smart_node.py:522-537; its transaction paths are commented out,
src/roles/user.py:171-199). No signing machinery: deployments that need
local signing can front this with any standard signer; the control-plane
protocol never depends on it.
"""

from __future__ import annotations

import json
import urllib.error
import urllib.request


class ChainError(RuntimeError):
    """JSON-RPC transport or EVM-level error."""


class ChainRpc:
    def __init__(self, url: str, timeout: float = 10.0):
        self.url = url
        self.timeout = timeout
        self._id = 0

    def request(self, method: str, params: list):
        self._id += 1
        body = json.dumps(
            {"jsonrpc": "2.0", "id": self._id, "method": method, "params": params}
        ).encode()
        req = urllib.request.Request(
            self.url, data=body, headers={"Content-Type": "application/json"}
        )
        try:
            with urllib.request.urlopen(req, timeout=self.timeout) as resp:
                payload = json.loads(resp.read())
        except (urllib.error.URLError, OSError, json.JSONDecodeError) as e:
            raise ChainError(f"rpc {method} failed: {e}") from e
        if payload.get("error"):
            raise ChainError(f"rpc {method}: {payload['error']}")
        return payload.get("result")

    # ------------------------------------------------------------- eth helpers
    def eth_call(self, to: str, data: bytes) -> bytes:
        result = self.request(
            "eth_call", [{"to": to, "data": "0x" + data.hex()}, "latest"]
        )
        return bytes.fromhex(result[2:]) if result and result != "0x" else b""

    def send_transaction(self, to: str, data: bytes, sender: str | None = None) -> str:
        tx = {"to": to, "data": "0x" + data.hex()}
        if sender:
            tx["from"] = sender
        return self.request("eth_sendTransaction", [tx])

    def get_transaction_receipt(self, tx_hash: str) -> dict | None:
        """Receipt (status + event logs) for a mined transaction; None
        while pending/unknown. The logs are how a transaction's "return
        value" actually reaches a JSON-RPC client (chain/registry.py
        request_job_onchain parses JobRequested from here)."""
        out = self.request("eth_getTransactionReceipt", [tx_hash])
        return out if isinstance(out, dict) else None

    def chain_id(self) -> int:
        return int(self.request("eth_chainId", []), 16)
