"""In-process mock EVM provider for hermetic chain tests.

The reference's only escape from its contract dependency is skipping it
(`off_chain_test=True`, src/p2p/smart_node.py:110,165). This mock instead
keeps the full RPC → calldata → ABI round-trip live: a threaded HTTP server
speaks JSON-RPC, and a Python object executes the registry contract's
semantics against the same selectors and codec `Web3Registry` emits. Tests
exercise the identical byte path a real node would, minus the EVM itself.
"""

from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from tensorlink_tpu.chain import abi
from tensorlink_tpu.chain.keccak import keccak256, selector

CONTRACT_ADDRESS = "0x" + "42" * 20


class MockRegistryContract:
    """Python-executed equivalent of the registry contract (see
    chain/registry.py module docstring for the Solidity interface)."""

    def __init__(self):
        self._validators: dict[str, dict] = {}  # nodeId -> record, insertion-ordered
        self._jobs: list[dict] = []  # on-chain job records (1-based ids)
        self._clock = 1_700_000_000  # deterministic "block time"
        # EVM-style event log emitted by the CURRENT execute() call; the
        # server moves these into the transaction's receipt (requestJob
        # emits JobRequested so submitters read their job id from the
        # receipt instead of racing a jobCount() re-read)
        self.pending_logs: list[dict] = []

    def execute(self, calldata: bytes) -> bytes:
        sel, args = calldata[:4], calldata[4:]
        if sel == selector("validatorCount()"):
            return abi.encode(["uint256"], [len(self._validators)])
        if sel == selector("validatorAt(uint256)"):
            [i] = abi.decode(["uint256"], args)
            rec = list(self._validators.values())[i]
            return abi.encode(
                ["string", "string", "uint256", "uint256", "uint256"],
                [rec["node_id"], rec["host"], rec["port"],
                 rec["reputation_milli"], rec["registered_at"]],
            )
        if sel == selector("isValidator(string)"):
            [node_id] = abi.decode(["string"], args)
            return abi.encode(["bool"], [node_id in self._validators])
        if sel == selector("registerValidator(string,string,uint256)"):
            node_id, host, port = abi.decode(["string", "string", "uint256"], args)
            self._clock += 1
            self._validators[node_id] = {
                "node_id": node_id, "host": host, "port": port,
                "reputation_milli": 1000, "registered_at": self._clock,
            }
            return b""
        if sel == selector("deregisterValidator(string)"):
            [node_id] = abi.decode(["string"], args)
            self._validators.pop(node_id, None)
            return b""
        if sel == selector("setReputation(string,uint256)"):
            node_id, rep = abi.decode(["string", "uint256"], args)
            if node_id in self._validators:
                self._validators[node_id]["reputation_milli"] = rep
            return b""
        # --- job/payment records (reference carried requestJob only as
        # commented-out intent, src/roles/user.py:50-64,171-199; here the
        # write path is live end to end against this contract)
        if sel == selector("jobCount()"):
            return abi.encode(["uint256"], [len(self._jobs)])
        if sel == selector("requestJob(string,uint256,uint256)"):
            user_id, capacity, payment = abi.decode(
                ["string", "uint256", "uint256"], args
            )
            self._clock += 1
            self._jobs.append({
                "user_id": user_id, "capacity": capacity,
                "payment_milli": payment, "completed": False,
                "requested_at": self._clock,
            })
            job_id = len(self._jobs)
            # event JobRequested(uint256 indexed jobId, string userId) —
            # the authoritative job-id channel for submitters (a tx return
            # value is unreadable over JSON-RPC; chain/registry.py)
            self.pending_logs.append({
                "address": CONTRACT_ADDRESS,
                "topics": [
                    "0x" + keccak256(b"JobRequested(uint256,string)").hex(),
                    "0x" + job_id.to_bytes(32, "big").hex(),
                ],
                "data": "0x" + abi.encode(["string"], [user_id]).hex(),
            })
            return abi.encode(["uint256"], [job_id])
        if sel == selector("completeJob(uint256)"):
            [job_id] = abi.decode(["uint256"], args)
            if not 1 <= job_id <= len(self._jobs):
                raise ValueError(f"unknown job {job_id}")
            self._jobs[job_id - 1]["completed"] = True
            return b""
        if sel == selector("jobAt(uint256)"):
            [job_id] = abi.decode(["uint256"], args)
            if not 1 <= job_id <= len(self._jobs):
                raise ValueError(f"unknown job {job_id}")
            rec = self._jobs[job_id - 1]
            return abi.encode(
                ["string", "uint256", "uint256", "bool"],
                [rec["user_id"], rec["capacity"], rec["payment_milli"],
                 rec["completed"]],
            )
        raise ValueError(f"unknown selector {sel.hex()}")


class MockChainServer:
    """Threaded JSON-RPC endpoint serving one MockRegistryContract."""

    def __init__(self, contract: MockRegistryContract | None = None):
        self.contract = contract or MockRegistryContract()
        self.calls: list[str] = []  # method log, for assertions
        self._receipts: dict[str, dict] = {}  # txHash -> receipt w/ logs
        self._tx_nonce = 0
        self._tx_lock = threading.Lock()  # handlers run on server threads
        outer = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *a):  # silence
                pass

            def do_POST(self):
                body = json.loads(self.rfile.read(int(self.headers["Content-Length"])))
                result, error = None, None
                try:
                    result = outer._dispatch(body["method"], body.get("params", []))
                except Exception as e:  # surfaces as a JSON-RPC error
                    error = {"code": -32000, "message": str(e)}
                reply = {"jsonrpc": "2.0", "id": body.get("id")}
                reply["error" if error else "result"] = error if error else result
                data = json.dumps(reply).encode()
                self.send_response(200)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(data)))
                self.end_headers()
                self.wfile.write(data)

        self._server = ThreadingHTTPServer(("127.0.0.1", 0), Handler)
        self._thread = threading.Thread(target=self._server.serve_forever, daemon=True)

    def _dispatch(self, method: str, params: list):
        self.calls.append(method)
        if method == "eth_chainId":
            return hex(31337)
        if method == "eth_call":
            calldata = bytes.fromhex(params[0]["data"][2:])
            if params[0]["to"].lower() != CONTRACT_ADDRESS:
                raise ValueError("unknown contract")
            with self._tx_lock:
                return "0x" + self.contract.execute(calldata).hex()
        if method == "eth_sendTransaction":
            tx = params[0]
            # same unknown-contract check as eth_call: a misconfigured
            # --chain-contract must fail on the write path too (advisor r3)
            if tx["to"].lower() != CONTRACT_ADDRESS:
                raise ValueError("unknown contract")
            calldata = bytes.fromhex(tx["data"][2:])
            # ThreadingHTTPServer handles each request on its own thread:
            # the reset -> execute -> receipt-snapshot sequence (and the
            # nonce bump) must be atomic, or a concurrent submitter's
            # reset clears this tx's logs and its receipt comes up empty —
            # the exact job-id race the JobRequested event exists to kill
            with self._tx_lock:
                self.contract.pending_logs = []
                self.contract.execute(calldata)
                # salt with a per-server nonce: identical calldata
                # submitted twice must not collide on tx hash (real
                # chains mix in the sender nonce), or the second receipt
                # would shadow the first
                self._tx_nonce += 1
                tx_hash = "0x" + keccak256(
                    calldata + self._tx_nonce.to_bytes(8, "big")
                ).hex()
                # receipt carries the events this execution emitted,
                # exactly like a real node — Web3Registry reads
                # JobRequested from here
                self._receipts[tx_hash] = {
                    "status": "0x1",
                    "transactionHash": tx_hash,
                    "logs": list(self.contract.pending_logs),
                }
            return tx_hash
        if method == "eth_getTransactionReceipt":
            with self._tx_lock:
                return self._receipts.get(
                    params[0], {"status": "0x1", "transactionHash": params[0]}
                )
        raise ValueError(f"unsupported method {method}")

    # ----------------------------------------------------------- lifecycle
    @property
    def url(self) -> str:
        return f"http://127.0.0.1:{self._server.server_address[1]}"

    def start(self) -> "MockChainServer":
        self._thread.start()
        return self

    def stop(self) -> None:
        self._server.shutdown()
        self._server.server_close()

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.stop()
