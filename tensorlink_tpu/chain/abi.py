"""Minimal Solidity ABI codec for the registry contract surface.

The reference carries a 1.5 MB generated ABI JSON and lets web3.py do the
encoding (reference config/SmartNodes.json, src/p2p/smart_node.py:165-179).
The registry interface here needs only a handful of types, so this is a
direct implementation of the ABI v2 head/tail encoding for:

    uintN / int-free unsigned ints, bool, address, bytesN, string, bytes,
    and one-dimensional dynamic arrays T[] of those.

Values are Python ints / bools / str (0x-hex for address) / bytes / lists.
"""

from __future__ import annotations

_WORD = 32


def _is_dynamic(typ: str) -> bool:
    if typ.endswith("[]"):
        return True
    return typ in ("string", "bytes")


def _pad_right(b: bytes) -> bytes:
    rem = len(b) % _WORD
    return b if rem == 0 else b + b"\x00" * (_WORD - rem)


def _encode_static(typ: str, value) -> bytes:
    if typ.startswith("uint") or typ == "int" or typ.startswith("int"):
        v = int(value)
        if v < 0:
            v += 1 << 256  # two's complement
        return v.to_bytes(_WORD, "big")
    if typ == "bool":
        return int(bool(value)).to_bytes(_WORD, "big")
    if typ == "address":
        h = value[2:] if isinstance(value, str) and value.startswith("0x") else value
        raw = bytes.fromhex(h) if isinstance(h, str) else bytes(h)
        if len(raw) != 20:
            raise ValueError(f"address must be 20 bytes, got {len(raw)}")
        return raw.rjust(_WORD, b"\x00")
    if typ.startswith("bytes") and typ != "bytes":  # bytesN
        n = int(typ[5:])
        raw = bytes(value)
        if len(raw) != n:
            raise ValueError(f"{typ} needs exactly {n} bytes")
        return raw.ljust(_WORD, b"\x00")
    raise ValueError(f"unsupported static type {typ}")


def _encode_one(typ: str, value) -> bytes:
    """Encoding of one value as it appears in a tail (dynamic) or head (static)."""
    if typ.endswith("[]"):
        elem = typ[:-2]
        return len(value).to_bytes(_WORD, "big") + encode([elem] * len(value), list(value))
    if typ == "string":
        raw = value.encode("utf-8")
        return len(raw).to_bytes(_WORD, "big") + _pad_right(raw)
    if typ == "bytes":
        raw = bytes(value)
        return len(raw).to_bytes(_WORD, "big") + _pad_right(raw)
    return _encode_static(typ, value)


def encode(types: list[str], values: list) -> bytes:
    """ABI-encode a flat argument list (head/tail layout)."""
    if len(types) != len(values):
        raise ValueError("types/values length mismatch")
    heads: list[bytes] = []
    tails: list[bytes] = []
    head_len = _WORD * len(types)
    for typ, val in zip(types, values):
        if _is_dynamic(typ):
            offset = head_len + sum(len(t) for t in tails)
            heads.append(offset.to_bytes(_WORD, "big"))
            tails.append(_encode_one(typ, val))
        else:
            heads.append(_encode_static(typ, val))
    return b"".join(heads) + b"".join(tails)


def _decode_static(typ: str, word: bytes):
    if typ.startswith("uint"):
        return int.from_bytes(word, "big")
    if typ.startswith("int"):
        v = int.from_bytes(word, "big")
        return v - (1 << 256) if v >= 1 << 255 else v
    if typ == "bool":
        return bool(int.from_bytes(word, "big"))
    if typ == "address":
        return "0x" + word[-20:].hex()
    if typ.startswith("bytes") and typ != "bytes":
        return word[: int(typ[5:])]
    raise ValueError(f"unsupported static type {typ}")


def _decode_one(typ: str, data: bytes, at: int):
    """Decode one dynamic value whose data begins at `at`."""
    if at + _WORD > len(data):
        raise ValueError(
            f"truncated returndata: dynamic {typ} head at {at} past "
            f"{len(data)} bytes"
        )
    if typ.endswith("[]"):
        elem = typ[:-2]
        n = int.from_bytes(data[at:at + _WORD], "big")
        body = data[at + _WORD:]
        # check n against the remaining bytes BEFORE [elem] * n — a
        # garbage count would otherwise allocate a 2**256-entry list
        if n * _WORD > len(body):
            raise ValueError(
                f"truncated returndata: {typ} declares {n} elements, "
                f"{len(body)} bytes remain"
            )
        return decode([elem] * n, body)
    length = int.from_bytes(data[at:at + _WORD], "big")
    raw = data[at + _WORD:at + _WORD + length]
    if len(raw) < length:
        raise ValueError(
            f"truncated returndata: {typ} declares {length} bytes, "
            f"{len(raw)} present"
        )
    return raw.decode("utf-8") if typ == "string" else raw


def decode(types: list[str], data: bytes) -> list:
    """ABI-decode a flat result list (the inverse of `encode`).

    Length-checked: a wrong contract returning short/garbage non-empty
    data must raise, not silently decode to zeros (advisor finding r3 —
    int.from_bytes of a short slice yields a bogus value)."""
    if len(data) < _WORD * len(types):
        raise ValueError(
            f"truncated returndata: {len(types)} head words need "
            f"{_WORD * len(types)} bytes, got {len(data)}"
        )
    out = []
    for i, typ in enumerate(types):
        word = data[_WORD * i:_WORD * (i + 1)]
        if _is_dynamic(typ):
            out.append(_decode_one(typ, data, int.from_bytes(word, "big")))
        else:
            out.append(_decode_static(typ, word))
    return out
