"""tensorlink-tpu: a TPU-native distributed deep-learning framework.

A ground-up re-design of the capabilities of tensorlink/tensorlink
(decentralized model partitioning + pipelined training across recruited
workers, reference: /root/reference/src) for TPU hardware:

- Data plane: jit-compiled XLA programs on a ``jax.sharding.Mesh`` with axes
  ``(data, pipe, model, seq)``; stage-to-stage activation exchange is
  ``jax.lax.ppermute`` over ICI instead of pickled tensors over TCP sockets
  (reference: src/p2p/torch_node.py:138-162).
- Control plane: asyncio typed-message overlay (handshake, DHT, job
  lifecycle, stats) — same protocol concepts as src/p2p/smart_node.py but
  with msgpack-typed messages and safetensors-style array shipping, never
  pickle.
- Roles: User / Worker / Validator (reference: src/roles) re-imagined so a
  "worker" is a host agent binding TPU chips as schedulable mesh capacity.
"""

__version__ = "0.1.0"

from tensorlink_tpu.config import (  # noqa: F401
    MeshConfig,
    TrainConfig,
    NodeConfig,
    FrameworkConfig,
)
from tensorlink_tpu.runtime.mesh import MeshRuntime, make_mesh  # noqa: F401
