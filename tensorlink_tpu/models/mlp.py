"""MLP classifier — the minimum end-to-end model (BASELINE.json config[0]:
2-layer MLP on MNIST, matching the reference's smallest implied workload)."""

from __future__ import annotations

from dataclasses import dataclass

import jax

from tensorlink_tpu.nn.module import Module, Sequential, Lambda
from tensorlink_tpu.nn.layers import Dense


@dataclass(frozen=True)
class MLPConfig:
    in_dim: int = 784
    hidden_dim: int = 256
    out_dim: int = 10
    num_layers: int = 2
    activation: str = "relu"


class MLP(Module):
    """Sequential stack so the pipeline partitioner can slice it into
    stages like any transformer."""

    def __init__(self, cfg: MLPConfig = MLPConfig()):
        super().__init__()
        self.cfg_obj = cfg
        act = {"relu": jax.nn.relu, "gelu": jax.nn.gelu}[cfg.activation]
        layers: list[Module] = []
        dims = (
            [cfg.in_dim]
            + [cfg.hidden_dim] * (cfg.num_layers - 1)
            + [cfg.out_dim]
        )
        for i in range(cfg.num_layers):
            layers.append(Dense(dims[i], dims[i + 1]))
            if i < cfg.num_layers - 1:
                layers.append(Lambda(act, name=cfg.activation))
        self.child("seq", Sequential(layers))

    @property
    def seq(self) -> Sequential:
        return self.children["seq"]  # type: ignore[return-value]

    def apply(self, params, x, **kw):
        return self.seq.apply(params["seq"], x)
