"""GPT-2, TPU-native (BASELINE.json config[2]: GPT-2-medium 8PP x 2DP)."""

from __future__ import annotations

from dataclasses import dataclass

import jax
import jax.numpy as jnp

from tensorlink_tpu.nn.module import Module
from tensorlink_tpu.nn.layers import Dropout, Embedding, LayerNorm
from tensorlink_tpu.nn.transformer import TransformerBlock, TransformerStack


@dataclass(frozen=True)
class GPT2Config:
    vocab_size: int = 50257
    dim: int = 768
    num_layers: int = 12
    num_heads: int = 12
    max_len: int = 1024
    dropout: float = 0.1
    layer_norm_eps: float = 1e-5
    attn_impl: str = "auto"  # auto | flash | reference | ring (seq-parallel)
    # fused q/k/v projection: one matmul per layer instead of three —
    # measured decode win at small batch (nn/attention.py qkv_fused)
    qkv_fused: bool = False

    @classmethod
    def small(cls) -> "GPT2Config":
        return cls()

    @classmethod
    def medium(cls) -> "GPT2Config":
        return cls(dim=1024, num_layers=24, num_heads=16)

    @classmethod
    def tiny(cls) -> "GPT2Config":
        return cls(vocab_size=128, dim=32, num_layers=2, num_heads=2, max_len=64)


class GPT2(Module):
    """Pre-LN decoder with learned positions and tied LM head."""

    def __init__(self, cfg: GPT2Config = GPT2Config()):
        super().__init__()
        self.cfg_obj = cfg
        self.child("wte", Embedding(cfg.vocab_size, cfg.dim))
        self.child("wpe", Embedding(cfg.max_len, cfg.dim))
        self.child("drop", Dropout(cfg.dropout))
        self.child(
            "blocks",
            TransformerStack(
                cfg.num_layers,
                TransformerBlock,
                dim=cfg.dim,
                num_heads=cfg.num_heads,
                hidden_dim=4 * cfg.dim,
                norm_style="pre",
                norm="layer",
                norm_eps=cfg.layer_norm_eps,
                activation="gelu",  # gelu_new (tanh approx)
                use_bias=True,
                causal=True,
                dropout=cfg.dropout,
                attn_impl=cfg.attn_impl,
                qkv_fused=cfg.qkv_fused,
            ),
        )
        self.child("ln_f", LayerNorm(cfg.dim, eps=cfg.layer_norm_eps))

    def apply(
        self,
        params,
        input_ids,
        *,
        caches=None,
        positions=None,
        mask=None,
        rng=None,
        train=False,
        logits: bool = True,
        **_,
    ):
        B, T = input_ids.shape
        if positions is None:
            if caches is not None:
                idx = caches[0]["attn"]["index"]
                if getattr(idx, "ndim", 0) == 1:
                    # per-row serving index ([B]): each row sits at its
                    # own position (bare [B] + [1,T] would broadcast to
                    # a bogus [B,T]-transposed table lookup)
                    idx = idx[:, None]
                positions = idx + jnp.arange(T)[None, :]
            else:
                positions = jnp.arange(T)[None, :]
        x = self.children["wte"].apply(params["wte"], input_ids)
        x = x + self.children["wpe"].apply(params["wpe"], positions)
        r0, r1 = jax.random.split(rng) if rng is not None else (None, None)
        x = self.children["drop"].apply(params["drop"], x, rng=r0, train=train)

        blocks = self.children["blocks"]
        if caches is not None:
            attn_caches = [c["attn"] for c in caches]
            x, new_attn = blocks.apply(
                params["blocks"], x, mask=mask, caches=attn_caches, rng=r1, train=train
            )
            new_caches = [{"attn": c} for c in new_attn]
        else:
            new_caches = None
            x = blocks.apply(params["blocks"], x, mask=mask, rng=r1, train=train)

        x = self.children["ln_f"].apply(params["ln_f"], x)
        out = self.children["wte"].attend(params["wte"], x) if logits else x
        if caches is not None:
            return out, new_caches
        return out

    def as_pipeline_parts(self, params):
        """Split into (embed, blocks, head) for the ShardedTrainer.
        The LM head stays tied to wte (head_fn sees all params)."""
        from tensorlink_tpu.parallel.engine import PipelineParts

        stack = self.children["blocks"]
        block = stack.blocks()[0]
        wte, wpe = self.children["wte"], self.children["wpe"]
        ln_f = self.children["ln_f"]

        drop = self.children["drop"]

        def embed_fn(emb_params, batch, rng=None):
            ids = batch["input_ids"]
            T = ids.shape[1]
            pos = jnp.arange(T)[None, :]
            tok = wte.apply(emb_params["wte"], ids)
            x = tok + wpe.apply(emb_params["wpe"], pos).astype(tok.dtype)
            return drop.apply({}, x, rng=rng, train=rng is not None)

        def head_fn(all_params, x, batch, rng=None):
            h = ln_f.apply(all_params["head"]["ln_f"], x)
            return wte.attend(all_params["embed"]["wte"], h)

        return PipelineParts(
            embed_fn=embed_fn,
            block=block,
            block_params=params["blocks"],
            block_fn=lambda bp, x, rng=None: block.apply(
                bp, x, rng=rng, train=rng is not None
            ),
            head_fn=head_fn,
            embed_params={"wte": params["wte"], "wpe": params["wpe"]},
            head_params={"ln_f": params["ln_f"]},
            # ln_f + tied-logits CE is a uniform per-token reduction, so
            # 1F1B may run the head per token shard under seq sharding
            head_per_token=True,
        )

    def init_caches(self, batch: int, max_len: int, dtype=jnp.bfloat16,
                    rolling: bool = False):
        stack = self.children["blocks"]
        return [
            {"attn": blk.children["attn"].init_cache(
                batch, max_len, dtype, rolling=rolling
            )}
            for blk in stack.blocks()
        ]
