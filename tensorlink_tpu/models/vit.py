"""ViT-B/16, TPU-native (BASELINE.json config[3]).

The reference would ship an opaque HF ``ViTForImageClassification`` as a
pickled submodule (src/p2p/torch_node.py:159-162); here the model is
native so pipeline stage slicing, TP specs, and spec-shipping apply.
Patch embedding is expressed as an unfold + matmul (not a conv) so the
whole model is Dense/matmul-shaped for the MXU.
"""

from __future__ import annotations

from dataclasses import dataclass

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from tensorlink_tpu.nn.module import Module, register_module_type
from tensorlink_tpu.nn.layers import Dense, Dropout, LayerNorm, _normal
from tensorlink_tpu.nn.transformer import TransformerBlock, TransformerStack


@dataclass(frozen=True)
class ViTConfig:
    image_size: int = 224
    patch_size: int = 16
    channels: int = 3
    dim: int = 768
    num_layers: int = 12
    num_heads: int = 12
    hidden_dim: int = 3072
    dropout: float = 0.0
    layer_norm_eps: float = 1e-12

    @classmethod
    def base_16(cls) -> "ViTConfig":
        return cls()

    @classmethod
    def tiny(cls) -> "ViTConfig":
        return cls(
            image_size=32,
            patch_size=8,
            dim=32,
            num_layers=2,
            num_heads=2,
            hidden_dim=64,
        )

    @property
    def num_patches(self) -> int:
        return (self.image_size // self.patch_size) ** 2


@register_module_type
class PatchEmbed(Module):
    """[B, H, W, C] images -> [B, N, dim] patch tokens via unfold+matmul."""

    def __init__(self, image_size: int, patch_size: int, channels: int, dim: int):
        super().__init__()
        self.image_size = image_size
        self.patch_size = patch_size
        self.channels = channels
        self.dim = dim

    def init(self, key):
        pdim = self.patch_size * self.patch_size * self.channels
        kw, _ = jax.random.split(key)
        return {
            "w": _normal(kw, (pdim, self.dim)),
            "b": jnp.zeros((self.dim,)),
        }

    def param_spec(self, model_axis: str = "model"):
        return {"w": P(None, None), "b": P(None)}

    def apply(self, params, images, **_):
        B, H, W, C = images.shape
        p = self.patch_size
        x = images.reshape(B, H // p, p, W // p, p, C)
        x = x.transpose(0, 1, 3, 2, 4, 5).reshape(B, (H // p) * (W // p), p * p * C)
        w = params["w"].astype(x.dtype)
        return x @ w + params["b"].astype(x.dtype)


class ViT(Module):
    """Pre-LN encoder with [CLS] token and learned position embeddings."""

    def __init__(self, cfg: ViTConfig = ViTConfig()):
        super().__init__()
        self.cfg_obj = cfg
        self.child(
            "patch", PatchEmbed(cfg.image_size, cfg.patch_size, cfg.channels, cfg.dim)
        )
        self.child("emb_drop", Dropout(cfg.dropout))
        self.child(
            "encoder",
            TransformerStack(
                cfg.num_layers,
                TransformerBlock,
                dim=cfg.dim,
                num_heads=cfg.num_heads,
                hidden_dim=cfg.hidden_dim,
                norm_style="pre",
                norm="layer",
                norm_eps=cfg.layer_norm_eps,
                activation="gelu_exact",
                use_bias=True,
                dropout=cfg.dropout,
            ),
        )
        self.child("final_norm", LayerNorm(cfg.dim, eps=cfg.layer_norm_eps))

    def init(self, key):
        kc, kp, krest = jax.random.split(key, 3)
        params = super().init(krest)
        cfg = self.cfg_obj
        params["cls_token"] = _normal(kc, (1, 1, cfg.dim))
        params["pos_emb"] = _normal(kp, (1, cfg.num_patches + 1, cfg.dim))
        return params

    def param_spec(self, model_axis: str = "model"):
        spec = super().param_spec(model_axis)
        spec["cls_token"] = P(None, None, None)
        spec["pos_emb"] = P(None, None, None)
        return spec

    def apply(self, params, images, *, rng=None, train=False, **_):
        B = images.shape[0]
        x = self.children["patch"].apply(params["patch"], images)
        cls = jnp.broadcast_to(
            params["cls_token"].astype(x.dtype), (B, 1, x.shape[-1])
        )
        x = jnp.concatenate([cls, x], axis=1)
        x = x + params["pos_emb"].astype(x.dtype)
        r0, r1 = jax.random.split(rng) if rng is not None else (None, None)
        x = self.children["emb_drop"].apply(params["emb_drop"], x, rng=r0, train=train)
        h = self.children["encoder"].apply(params["encoder"], x, rng=r1, train=train)
        h = self.children["final_norm"].apply(params["final_norm"], h)
        return {"last_hidden_state": h, "pooled": h[:, 0]}


class ViTClassifier(Module):
    """ViTForImageClassification equivalent (head on the [CLS] token)."""

    def __init__(self, cfg: ViTConfig, num_classes: int):
        super().__init__()
        self.num_classes = num_classes
        self.child("vit", ViT(cfg))
        self.child("head", Dense(cfg.dim, num_classes))

    def apply(self, params, images, *, rng=None, train=False, **kw):
        out = self.children["vit"].apply(
            params["vit"], images, rng=rng, train=train, **kw
        )
        return self.children["head"].apply(params["head"], out["pooled"])


def vit_pipeline_parts(model: ViT, params: dict, num_classes_head=None):
    """Split a ViT (or ViTClassifier param tree) into pipeline parts, same
    contract as bert_pipeline_parts: embed -> stacked blocks -> head."""
    from tensorlink_tpu.parallel.engine import PipelineParts

    vit = model
    vp = params if num_classes_head is None else params["vit"]
    stack = vit.children["encoder"]
    block = stack.blocks()[0]

    emb_drop = vit.children["emb_drop"]

    def embed_fn(emb_params, batch, rng=None):
        images = batch["images"]
        B = images.shape[0]
        x = vit.children["patch"].apply(emb_params["patch"], images)
        cls = jnp.broadcast_to(
            emb_params["cls_token"].astype(x.dtype), (B, 1, x.shape[-1])
        )
        x = jnp.concatenate([cls, x], axis=1)
        x = x + emb_params["pos_emb"].astype(x.dtype)
        return emb_drop.apply({}, x, rng=rng, train=rng is not None)

    if num_classes_head is not None:
        def head_fn(all_params, x, batch, rng=None):
            h = vit.children["final_norm"].apply(
                all_params["head"]["final_norm"], x
            )
            hw = all_params["head"]["cls"]
            return h[:, 0] @ hw["w"].astype(h.dtype) + hw["b"].astype(h.dtype)

        head_params = {"final_norm": vp["final_norm"], "cls": params["head"]}
    else:
        def head_fn(all_params, x, batch, rng=None):
            return vit.children["final_norm"].apply(
                all_params["head"]["final_norm"], x
            )

        head_params = {"final_norm": vp["final_norm"]}

    return PipelineParts(
        embed_fn=embed_fn,
        block=block,
        block_params=vp["encoder"],
        block_fn=lambda blk_p, x, rng=None: block.apply(
            blk_p, x, rng=rng, train=rng is not None
        ),
        head_fn=head_fn,
        # the classifier head pools the CLS patch — position-selective,
        # not a uniform token reduction (same as BERT's CLS pooling)
        head_per_token=False if num_classes_head is not None else None,
        embed_params={
            "patch": vp["patch"],
            "cls_token": vp["cls_token"],
            "pos_emb": vp["pos_emb"],
        },
        head_params=head_params,
    )
