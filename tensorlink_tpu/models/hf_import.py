"""HuggingFace checkpoint import.

The reference ships live pickled ``nn.Module`` objects to workers
(src/p2p/torch_node.py:159-162). Here model code is native and only
*weights* move: a flat ``{name: numpy array}`` state dict — from
``safetensors`` files or a torch ``state_dict()`` — is remapped into the
native param pytree. torch Linear weights are [out, in] and transposed;
GPT-2 Conv1D weights are already [in, out].
"""

from __future__ import annotations

from typing import Mapping

import numpy as np
import jax.numpy as jnp

from tensorlink_tpu.models.bert import BertConfig
from tensorlink_tpu.models.gpt2 import GPT2Config
from tensorlink_tpu.models.vit import ViTConfig


def _t(x) -> np.ndarray:  # torch Linear -> our [in, out]
    return np.asarray(x).T


def _a(x) -> np.ndarray:
    return np.asarray(x)


def load_safetensors(path: str) -> dict[str, np.ndarray]:
    from safetensors.numpy import load_file

    return load_file(path)


def strip_prefix(sd: Mapping[str, np.ndarray], prefix: str) -> dict[str, np.ndarray]:
    return {
        (k[len(prefix):] if k.startswith(prefix) else k): v for k, v in sd.items()
    }


def bert_params_from_hf(sd: Mapping[str, np.ndarray], cfg: BertConfig) -> dict:
    """Map an HF BertModel state dict onto the native `Bert` param tree."""
    p: dict = {
        "tok_emb": {"table": _a(sd["embeddings.word_embeddings.weight"])},
        "pos_emb": {"table": _a(sd["embeddings.position_embeddings.weight"])},
        "type_emb": {"table": _a(sd["embeddings.token_type_embeddings.weight"])},
        "emb_norm": {
            "scale": _a(sd["embeddings.LayerNorm.weight"]),
            "bias": _a(sd["embeddings.LayerNorm.bias"]),
        },
        "emb_drop": {},
        "encoder": {},
        "pooler": {
            "w": _t(sd["pooler.dense.weight"]),
            "b": _a(sd["pooler.dense.bias"]),
        },
    }
    for i in range(cfg.num_layers):
        pre = f"encoder.layer.{i}."
        p["encoder"][str(i)] = {
            "attn": {
                "q": {
                    "w": _t(sd[pre + "attention.self.query.weight"]),
                    "b": _a(sd[pre + "attention.self.query.bias"]),
                },
                "k": {
                    "w": _t(sd[pre + "attention.self.key.weight"]),
                    "b": _a(sd[pre + "attention.self.key.bias"]),
                },
                "v": {
                    "w": _t(sd[pre + "attention.self.value.weight"]),
                    "b": _a(sd[pre + "attention.self.value.bias"]),
                },
                "o": {
                    "w": _t(sd[pre + "attention.output.dense.weight"]),
                    "b": _a(sd[pre + "attention.output.dense.bias"]),
                },
            },
            "norm1": {
                "scale": _a(sd[pre + "attention.output.LayerNorm.weight"]),
                "bias": _a(sd[pre + "attention.output.LayerNorm.bias"]),
            },
            "mlp": {
                "up": {
                    "w": _t(sd[pre + "intermediate.dense.weight"]),
                    "b": _a(sd[pre + "intermediate.dense.bias"]),
                },
                "down": {
                    "w": _t(sd[pre + "output.dense.weight"]),
                    "b": _a(sd[pre + "output.dense.bias"]),
                },
                "drop": {},
            },
            "norm2": {
                "scale": _a(sd[pre + "output.LayerNorm.weight"]),
                "bias": _a(sd[pre + "output.LayerNorm.bias"]),
            },
            "drop": {},
        }
    return _to_jnp(p)


def gpt2_params_from_hf(sd: Mapping[str, np.ndarray], cfg: GPT2Config) -> dict:
    """Map an HF GPT2Model state dict onto the native `GPT2` param tree."""
    p: dict = {
        "wte": {"table": _a(sd["wte.weight"])},
        "wpe": {"table": _a(sd["wpe.weight"])},
        "drop": {},
        "blocks": {},
        "ln_f": {
            "scale": _a(sd["ln_f.weight"]),
            "bias": _a(sd["ln_f.bias"]),
        },
    }
    D = cfg.dim
    for i in range(cfg.num_layers):
        pre = f"h.{i}."
        c_attn_w = _a(sd[pre + "attn.c_attn.weight"])  # [in, 3D] (Conv1D)
        c_attn_b = _a(sd[pre + "attn.c_attn.bias"])
        qw, kw, vw = c_attn_w[:, :D], c_attn_w[:, D : 2 * D], c_attn_w[:, 2 * D :]
        qb, kb, vb = c_attn_b[:D], c_attn_b[D : 2 * D], c_attn_b[2 * D :]
        p["blocks"][str(i)] = {
            "norm1": {
                "scale": _a(sd[pre + "ln_1.weight"]),
                "bias": _a(sd[pre + "ln_1.bias"]),
            },
            "norm2": {
                "scale": _a(sd[pre + "ln_2.weight"]),
                "bias": _a(sd[pre + "ln_2.bias"]),
            },
            "attn": {
                "q": {"w": qw, "b": qb},
                "k": {"w": kw, "b": kb},
                "v": {"w": vw, "b": vb},
                "o": {
                    "w": _a(sd[pre + "attn.c_proj.weight"]),
                    "b": _a(sd[pre + "attn.c_proj.bias"]),
                },
            },
            "mlp": {
                "up": {
                    "w": _a(sd[pre + "mlp.c_fc.weight"]),
                    "b": _a(sd[pre + "mlp.c_fc.bias"]),
                },
                "down": {
                    "w": _a(sd[pre + "mlp.c_proj.weight"]),
                    "b": _a(sd[pre + "mlp.c_proj.bias"]),
                },
                "drop": {},
            },
            "drop": {},
        }
    return _to_jnp(p)


def vit_params_from_hf(sd: Mapping[str, np.ndarray], cfg: "ViTConfig") -> dict:
    """Map an HF ViTModel state dict onto the native `ViT` param tree.

    The HF conv patch projection weight is [D, C, P, P]; our unfold+matmul
    layout wants [P*P*C, D] with patch pixels varying fastest in
    (row, col, channel) order — matching PatchEmbed's reshape.
    """
    conv_w = _a(sd["embeddings.patch_embeddings.projection.weight"])
    D, C, P_, _ = conv_w.shape
    patch_w = conv_w.transpose(2, 3, 1, 0).reshape(P_ * P_ * C, D)
    p: dict = {
        "cls_token": _a(sd["embeddings.cls_token"]),
        "pos_emb": _a(sd["embeddings.position_embeddings"]),
        "patch": {
            "w": patch_w,
            "b": _a(sd["embeddings.patch_embeddings.projection.bias"]),
        },
        "emb_drop": {},
        "encoder": {},
        "final_norm": {
            "scale": _a(sd["layernorm.weight"]),
            "bias": _a(sd["layernorm.bias"]),
        },
    }
    for i in range(cfg.num_layers):
        pre = f"encoder.layer.{i}."
        p["encoder"][str(i)] = {
            "norm1": {
                "scale": _a(sd[pre + "layernorm_before.weight"]),
                "bias": _a(sd[pre + "layernorm_before.bias"]),
            },
            "norm2": {
                "scale": _a(sd[pre + "layernorm_after.weight"]),
                "bias": _a(sd[pre + "layernorm_after.bias"]),
            },
            "attn": {
                "q": {
                    "w": _t(sd[pre + "attention.attention.query.weight"]),
                    "b": _a(sd[pre + "attention.attention.query.bias"]),
                },
                "k": {
                    "w": _t(sd[pre + "attention.attention.key.weight"]),
                    "b": _a(sd[pre + "attention.attention.key.bias"]),
                },
                "v": {
                    "w": _t(sd[pre + "attention.attention.value.weight"]),
                    "b": _a(sd[pre + "attention.attention.value.bias"]),
                },
                "o": {
                    "w": _t(sd[pre + "attention.output.dense.weight"]),
                    "b": _a(sd[pre + "attention.output.dense.bias"]),
                },
            },
            "mlp": {
                "up": {
                    "w": _t(sd[pre + "intermediate.dense.weight"]),
                    "b": _a(sd[pre + "intermediate.dense.bias"]),
                },
                "down": {
                    "w": _t(sd[pre + "output.dense.weight"]),
                    "b": _a(sd[pre + "output.dense.bias"]),
                },
                "drop": {},
            },
            "drop": {},
        }
    return _to_jnp(p)


def llama_params_from_hf(sd: Mapping[str, np.ndarray], cfg: "LlamaConfig") -> dict:
    """Map an HF ``LlamaForCausalLM`` state dict onto the native `Llama`
    param tree. Expects full-model keys (``model.embed_tokens...`` +
    ``lm_head.weight``). Tied-embedding checkpoints (e.g. llama-3.2-1b)
    may omit ``lm_head.weight``; the embedding is reused then.

    ``MistralForCausalLM`` shares this exact layout (Mistral = Llama
    trunk + sliding window, which is config not weights — set
    ``LlamaConfig.attn_window``); windowed-logit parity vs HF is pinned
    in tests/test_models.py::test_mistral_parity_vs_hf."""
    p: dict = {
        "tok_emb": {"table": _a(sd["model.embed_tokens.weight"])},
        "blocks": {},
        "norm_f": {"scale": _a(sd["model.norm.weight"])},
        "lm_head": {
            "w": _t(sd.get("lm_head.weight", sd["model.embed_tokens.weight"]))
        },
    }
    for i in range(cfg.num_layers):
        pre = f"model.layers.{i}."
        p["blocks"][str(i)] = {
            "norm1": {"scale": _a(sd[pre + "input_layernorm.weight"])},
            "norm2": {"scale": _a(sd[pre + "post_attention_layernorm.weight"])},
            "attn": {
                "q": {"w": _t(sd[pre + "self_attn.q_proj.weight"])},
                "k": {"w": _t(sd[pre + "self_attn.k_proj.weight"])},
                "v": {"w": _t(sd[pre + "self_attn.v_proj.weight"])},
                "o": {"w": _t(sd[pre + "self_attn.o_proj.weight"])},
            },
            "mlp": {
                "up": {"w": _t(sd[pre + "mlp.up_proj.weight"])},
                "gate": {"w": _t(sd[pre + "mlp.gate_proj.weight"])},
                "down": {"w": _t(sd[pre + "mlp.down_proj.weight"])},
                "drop": {},
            },
            "drop": {},
        }
    return _to_jnp(p)


def t5_params_from_hf(sd: Mapping[str, np.ndarray], cfg) -> dict:
    """Map an HF T5ForConditionalGeneration state dict onto the native
    `T5` param tree (models/t5.py). The FF is the shared FeedForward:
    v1.0 maps HF `wi` -> `up`; v1.1 gated maps HF `wi_0` (the activated
    branch) -> `gate` and `wi_1` (the linear multiplier) -> `up`."""

    def block(side: str, i: int, cross: bool) -> dict:
        pre = f"{side}.block.{i}.layer."
        ff_l = 2 if cross else 1
        ffp = pre + f"{ff_l}.DenseReluDense."
        out: dict = {
            "norm1": {"scale": _a(sd[pre + "0.layer_norm.weight"])},
            "attn": {
                "q": {"w": _t(sd[pre + "0.SelfAttention.q.weight"])},
                "k": {"w": _t(sd[pre + "0.SelfAttention.k.weight"])},
                "v": {"w": _t(sd[pre + "0.SelfAttention.v.weight"])},
                "o": {"w": _t(sd[pre + "0.SelfAttention.o.weight"])},
            },
            "norm2": {"scale": _a(sd[pre + f"{ff_l}.layer_norm.weight"])},
            "ff": (
                {
                    "gate": {"w": _t(sd[ffp + "wi_0.weight"])},
                    "up": {"w": _t(sd[ffp + "wi_1.weight"])},
                    "down": {"w": _t(sd[ffp + "wo.weight"])},
                    "drop": {},
                }
                if cfg.gated_ff
                else {
                    "up": {"w": _t(sd[ffp + "wi.weight"])},
                    "down": {"w": _t(sd[ffp + "wo.weight"])},
                    "drop": {},
                }
            ),
            "drop": {},
        }
        if cross:
            out["norm_x"] = {"scale": _a(sd[pre + "1.layer_norm.weight"])}
            out["xattn"] = {
                "q": {"w": _t(sd[pre + "1.EncDecAttention.q.weight"])},
                "k": {"w": _t(sd[pre + "1.EncDecAttention.k.weight"])},
                "v": {"w": _t(sd[pre + "1.EncDecAttention.v.weight"])},
                "o": {"w": _t(sd[pre + "1.EncDecAttention.o.weight"])},
            }
        return out

    p: dict = {
        "shared": {"table": _a(sd["shared.weight"])},
        "enc_rel": {"w": _a(sd[
            "encoder.block.0.layer.0.SelfAttention."
            "relative_attention_bias.weight"
        ])},
        "dec_rel": {"w": _a(sd[
            "decoder.block.0.layer.0.SelfAttention."
            "relative_attention_bias.weight"
        ])},
        "enc_norm": {"scale": _a(sd["encoder.final_layer_norm.weight"])},
        "dec_norm": {"scale": _a(sd["decoder.final_layer_norm.weight"])},
        "drop": {},
    }
    for i in range(cfg.num_layers):
        p[f"enc{i}"] = block("encoder", i, cross=False)
        p[f"dec{i}"] = block("decoder", i, cross=True)
    if not cfg.tie_word_embeddings:
        p["lm_head"] = {"w": _t(sd["lm_head.weight"])}
    return _to_jnp(p)


def _to_jnp(tree):
    import jax

    return jax.tree.map(lambda x: jnp.asarray(x, jnp.float32), tree)


def torch_state_dict_to_numpy(model) -> dict[str, np.ndarray]:
    """torch nn.Module -> {name: numpy} (cpu)."""
    return {k: v.detach().cpu().numpy() for k, v in model.state_dict().items()}
