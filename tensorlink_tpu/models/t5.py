"""T5 encoder-decoder family, TPU-native.

The reference's model scope is "any HF module it can pickle"
(src/ml/distributed.py:305-378 walks arbitrary module trees); this
framework builds model families from its own blocks instead, and T5 adds
the encoder-decoder shape the zoo lacked: bidirectional encoder,
causal decoder with cross-attention, bucketed relative position biases
shared across layers, RMS layer norm, and the no-softmax-scale attention
convention (folded into T5's init). v1.0 (ReLU FF) and v1.1 (gated-GeLU)
are both expressible via ``gated_ff``.

TP: the same Megatron col/row ``PartitionSpec``s as every other family
(q/k/v/o + FF splits) — `param_spec` composes per block. The engine's
pipeline path needs a homogeneous block stack, which an encoder-decoder
is not; T5 trains via plain (sharded) apply and serves via
``greedy_decode``: encoder once, per-layer cross k/v projected once,
self-attention KV-cached — one single-token decoder pass per emitted
token inside one jitted scan.
"""

from __future__ import annotations

from dataclasses import dataclass

import jax
import jax.numpy as jnp
import numpy as np

from tensorlink_tpu.nn.attention import MultiHeadAttention
from tensorlink_tpu.nn.layers import Dense, Dropout, Embedding, RMSNorm, _normal
from tensorlink_tpu.nn.module import Module
from tensorlink_tpu.nn.transformer import FeedForward


@dataclass(frozen=True)
class T5Config:
    vocab_size: int = 32128
    dim: int = 512
    num_layers: int = 6  # per side (encoder AND decoder)
    num_heads: int = 8
    head_dim: int = 64
    hidden_dim: int = 2048
    rel_buckets: int = 32
    rel_max_distance: int = 128
    dropout: float = 0.1
    rms_eps: float = 1e-6
    gated_ff: bool = False  # False = v1.0 ReLU; True = v1.1 gated-GeLU
    tie_word_embeddings: bool = True  # v1.0 ties (and rescales logits)

    @classmethod
    def small(cls) -> "T5Config":
        return cls()

    @classmethod
    def tiny(cls) -> "T5Config":
        return cls(vocab_size=128, dim=32, num_layers=2, num_heads=2,
                   head_dim=16, hidden_dim=64, rel_buckets=8,
                   rel_max_distance=16, dropout=0.0)


def relative_position_bucket(
    rel: jax.Array, *, bidirectional: bool, num_buckets: int, max_distance: int
) -> jax.Array:
    """T5's log-bucketed relative positions (key_pos - query_pos).

    Mirrors the published bucketing exactly: half the buckets for exact
    small offsets, the rest log-spaced up to max_distance; bidirectional
    (encoder) splits buckets between signs, causal (decoder) uses only
    non-positive offsets.
    """
    ret = jnp.zeros_like(rel)
    n = -rel
    if bidirectional:
        num_buckets //= 2
        ret = ret + (n < 0).astype(jnp.int32) * num_buckets
        n = jnp.abs(n)
    else:
        n = jnp.maximum(n, 0)
    max_exact = num_buckets // 2
    is_small = n < max_exact
    # n=0 is covered by is_small, but where() evaluates both branches —
    # clamp so log never sees 0 (no epsilon: it could flip a bucket at
    # an exact boundary and break bitwise parity with the published
    # bucketing)
    safe_n = jnp.maximum(n, 1).astype(jnp.float32)
    log_big = max_exact + (
        jnp.log(safe_n / max_exact)
        / np.log(max_distance / max_exact)
        * (num_buckets - max_exact)
    ).astype(jnp.int32)
    log_big = jnp.minimum(log_big, num_buckets - 1)
    return ret + jnp.where(is_small, n, log_big)


class RelativePositionBias(Module):
    """[H, buckets] embedding -> additive attention bias [1, H, Tq, Tk].
    ONE instance per stack, shared by every layer (T5 convention: only
    layer 0 holds the table)."""

    def __init__(self, num_heads: int, num_buckets: int, max_distance: int,
                 bidirectional: bool):
        super().__init__()
        self.num_heads = num_heads
        self.num_buckets = num_buckets
        self.max_distance = max_distance
        self.bidirectional = bidirectional

    def init(self, key):
        return {"w": _normal(key, (self.num_buckets, self.num_heads))}

    def param_spec(self, model_axis: str = "model"):
        from jax.sharding import PartitionSpec as P

        # heads are TP-split in attention; the bias table is tiny —
        # replicate and let XLA slice the head dim with the logits
        return {"w": P()}

    def apply(self, params, q_pos, k_pos, **_):
        """q_pos [Tq], k_pos [Tk] (absolute positions) -> [1, H, Tq, Tk]."""
        rel = k_pos[None, :] - q_pos[:, None]  # [Tq, Tk]
        bucket = relative_position_bucket(
            rel, bidirectional=self.bidirectional,
            num_buckets=self.num_buckets, max_distance=self.max_distance,
        )
        bias = params["w"][bucket]  # [Tq, Tk, H]
        return bias.transpose(2, 0, 1)[None]


class T5Block(Module):
    """Pre-RMSNorm residual block: self-attn [+ cross-attn] + FF.
    The relative-position bias arrives from the stack (shared table)."""

    def __init__(self, cfg: T5Config, *, causal: bool, cross: bool):
        super().__init__()
        self.causal = causal
        self.cross = cross
        mk_attn = lambda: MultiHeadAttention(  # noqa: E731
            cfg.dim, cfg.num_heads, head_dim=cfg.head_dim, use_bias=False,
            causal=False,  # causality rides the explicit mask (rel bias
            # needs the same [Tq, Tk] geometry anyway)
            attn_impl="reference", scale=1.0,
        )
        self.child("norm1", RMSNorm(cfg.dim, eps=cfg.rms_eps))
        self.child("attn", mk_attn())
        if cross:
            self.child("norm_x", RMSNorm(cfg.dim, eps=cfg.rms_eps))
            self.child("xattn", mk_attn())
        self.child("norm2", RMSNorm(cfg.dim, eps=cfg.rms_eps))
        # the shared FeedForward covers both T5 variants: v1.0 is the
        # ungated ReLU MLP, v1.1 is act(gate(x)) * up(x) with gelu_new —
        # exactly HF's act(wi_0(x)) * wi_1(x)
        self.child(
            "ff",
            FeedForward(
                cfg.dim, cfg.hidden_dim,
                activation="gelu" if cfg.gated_ff else "relu",
                use_bias=False, gated=cfg.gated_ff, dropout=cfg.dropout,
            ),
        )
        self.child("drop", Dropout(cfg.dropout))

    def apply(self, params, x, *, mask=None, bias=None, memory=None,
              memory_mask=None, cross_kv=None, cache=None, rng=None,
              train=False, **_):
        drop = self.children["drop"]
        r1 = r2 = r3 = r4 = None
        if rng is not None:
            # 4 independent streams: self-attn residual, cross residual,
            # FF-internal, FF residual — sharing a key between the last
            # two would correlate (at hidden==dim, equate) their masks
            r1, r2, r3, r4 = jax.random.split(rng, 4)
        h = self.children["norm1"].apply(params["norm1"], x)
        if cache is None:
            a = self.children["attn"].apply(
                params["attn"], h, mask=mask, bias=bias
            )
            new_cache = None
        else:
            a, new_cache = self.children["attn"].apply(
                params["attn"], h, mask=mask, bias=bias, cache=cache
            )
        x = x + drop.apply({}, a, rng=r1, train=train)
        if self.cross:
            h = self.children["norm_x"].apply(params["norm_x"], x)
            a = self.children["xattn"].apply(
                params["xattn"], h, kv=memory, precomputed_kv=cross_kv,
                mask=memory_mask,
            )
            x = x + drop.apply({}, a, rng=r2, train=train)
        h = self.children["norm2"].apply(params["norm2"], x)
        f = self.children["ff"].apply(params["ff"], h, rng=r3, train=train)
        x = x + drop.apply({}, f, rng=r4, train=train)
        if cache is not None:
            return x, new_cache
        return x


class T5(Module):
    """Encoder-decoder; ``apply`` returns decoder LM logits."""

    def __init__(self, cfg: T5Config = T5Config()):
        super().__init__()
        self.cfg_obj = cfg
        self.child("shared", Embedding(cfg.vocab_size, cfg.dim))
        self.child("enc_rel", RelativePositionBias(
            cfg.num_heads, cfg.rel_buckets, cfg.rel_max_distance,
            bidirectional=True,
        ))
        self.child("dec_rel", RelativePositionBias(
            cfg.num_heads, cfg.rel_buckets, cfg.rel_max_distance,
            bidirectional=False,
        ))
        for i in range(cfg.num_layers):
            self.child(f"enc{i}", T5Block(cfg, causal=False, cross=False))
        self.child("enc_norm", RMSNorm(cfg.dim, eps=cfg.rms_eps))
        for i in range(cfg.num_layers):
            self.child(f"dec{i}", T5Block(cfg, causal=True, cross=True))
        self.child("dec_norm", RMSNorm(cfg.dim, eps=cfg.rms_eps))
        if not cfg.tie_word_embeddings:
            self.child("lm_head", Dense(cfg.dim, cfg.vocab_size,
                                        use_bias=False, shard="col"))
        self.child("drop", Dropout(cfg.dropout))

    # ------------------------------------------------------------- encoder
    def encode(self, params, input_ids, *, attention_mask=None, rng=None,
               train=False):
        cfg = self.cfg_obj
        T = input_ids.shape[1]
        x = self.children["shared"].apply(params["shared"], input_ids)
        x = self.children["drop"].apply({}, x, rng=rng, train=train)
        pos = jnp.arange(T)
        bias = self.children["enc_rel"].apply(params["enc_rel"], pos, pos)
        mask = None
        if attention_mask is not None:
            mask = attention_mask[:, None, None, :].astype(bool)
        for i in range(cfg.num_layers):
            r = jax.random.fold_in(rng, i) if rng is not None else None
            x = self.children[f"enc{i}"].apply(
                params[f"enc{i}"], x, mask=mask, bias=bias, rng=r,
                train=train,
            )
        return self.children["enc_norm"].apply(params["enc_norm"], x)

    # ------------------------------------------------------------- decoder
    def _dec_mask(self, B, T):
        tri = jnp.tril(jnp.ones((T, T), bool))
        return jnp.broadcast_to(tri[None, None], (B, 1, T, T))

    def decode(self, params, decoder_ids, memory, *, memory_mask=None,
               decoder_attention_mask=None, rng=None, train=False):
        cfg = self.cfg_obj
        B, T = decoder_ids.shape
        x = self.children["shared"].apply(params["shared"], decoder_ids)
        x = self.children["drop"].apply({}, x, rng=rng, train=train)
        pos = jnp.arange(T)
        bias = self.children["dec_rel"].apply(params["dec_rel"], pos, pos)
        mask = self._dec_mask(B, T)
        if decoder_attention_mask is not None:
            # padded decoder batches: real positions must not attend to
            # pad keys that precede them under the causal mask
            mask = mask & decoder_attention_mask[:, None, None, :].astype(bool)
        mm = None
        if memory_mask is not None:
            mm = memory_mask[:, None, None, :].astype(bool)
        for i in range(cfg.num_layers):
            r = (
                jax.random.fold_in(rng, 100 + i) if rng is not None else None
            )
            x = self.children[f"dec{i}"].apply(
                params[f"dec{i}"], x, mask=mask, bias=bias, memory=memory,
                memory_mask=mm, rng=r, train=train,
            )
        x = self.children["dec_norm"].apply(params["dec_norm"], x)
        return self._lm_logits(params, x)

    def _lm_logits(self, params, x):
        cfg = self.cfg_obj
        if cfg.tie_word_embeddings:
            # T5 rescales tied logits by d^-0.5 (the missing attention
            # scale's twin convention)
            x = x * (cfg.dim ** -0.5)
            return self.children["shared"].attend(params["shared"], x)
        return self.children["lm_head"].apply(params["lm_head"], x)

    def apply(self, params, input_ids, decoder_input_ids, *,
              attention_mask=None, decoder_attention_mask=None, rng=None,
              train=False, **_):
        r_enc = r_dec = None
        if rng is not None:
            r_enc, r_dec = jax.random.split(rng)
        memory = self.encode(
            params, input_ids, attention_mask=attention_mask, rng=r_enc,
            train=train,
        )
        return self.decode(
            params, decoder_input_ids, memory, memory_mask=attention_mask,
            decoder_attention_mask=decoder_attention_mask,
            rng=r_dec, train=train,
        )

    # ------------------------------------------------------------ serving
    def greedy_decode(self, params, input_ids, *, attention_mask=None,
                      max_new_tokens: int = 32, start_id: int = 0):
        """Greedy seq2seq generation, KV-cached: the encoder runs once,
        each decoder layer's cross-attention k/v are projected ONCE
        (``project_kv``), and self-attention reads its per-layer cache —
        one single-token decoder pass per emitted token inside one
        jitted ``lax.scan``. Exact vs re-running ``decode()`` on the
        emitted prefix: the rel-pos bias row for query position t is
        sliced from the same table, and the cache's validity mask plays
        the causal mask's role for the lone query."""
        cfg = self.cfg_obj
        B = input_ids.shape[0]
        L = int(max_new_tokens) + 1
        memory = self.encode(params, input_ids,
                             attention_mask=attention_mask)
        mm = None
        if attention_mask is not None:
            mm = attention_mask[:, None, None, :].astype(bool)
        # per-layer one-time setup: cross k/v + empty self-attn caches
        cross_kv = [
            self.children[f"dec{i}"].children["xattn"].project_kv(
                params[f"dec{i}"]["xattn"], memory
            )
            for i in range(cfg.num_layers)
        ]
        caches = [
            self.children[f"dec{i}"].children["attn"].init_cache(
                B, L, dtype=memory.dtype
            )
            for i in range(cfg.num_layers)
        ]
        # full [L, L] rel-pos table once; row t is step t's bias
        pos = jnp.arange(L)
        bias_full = self.children["dec_rel"].apply(
            params["dec_rel"], pos, pos
        )

        def step(carry, t):
            tok, caches = carry  # current input token [B]
            x = self.children["shared"].apply(params["shared"], tok[:, None])
            bias = jax.lax.dynamic_slice_in_dim(
                bias_full, t, 1, axis=2
            )  # [1, H, 1, L]
            new_caches = []
            h = x
            for i in range(cfg.num_layers):
                h, c = self.children[f"dec{i}"].apply(
                    params[f"dec{i}"], h, bias=bias, cross_kv=cross_kv[i],
                    memory_mask=mm, cache=caches[i],
                )
                new_caches.append(c)
            h = self.children["dec_norm"].apply(params["dec_norm"], h)
            logits = self._lm_logits(params, h)[:, 0]
            nxt = jnp.argmax(logits.astype(jnp.float32), axis=-1).astype(
                jnp.int32
            )
            return (nxt, new_caches), nxt

        tok0 = jnp.full((B,), start_id, jnp.int32)
        _, toks = jax.lax.scan(
            step, (tok0, caches), jnp.arange(max_new_tokens)
        )
        return np.asarray(toks.T)
