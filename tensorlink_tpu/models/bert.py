"""BERT, TPU-native.

Replaces the reference's opaque HF ``BertModel`` submodule shipping
(reference workload: tests/ml/test_full_train.py:56-175 fine-tunes
``BertForSequenceClassification``) with a native implementation whose
blocks are the framework's own `TransformerBlock`s — so the pipeline
partitioner, TP specs, and spec-shipping all apply directly. Weights
import from HF checkpoints via models/hf_import.py.
"""

from __future__ import annotations

from dataclasses import dataclass

import jax
import jax.numpy as jnp

from tensorlink_tpu.nn.module import Module
from tensorlink_tpu.nn.layers import Dense, Dropout, Embedding, LayerNorm
from tensorlink_tpu.nn.transformer import TransformerBlock, TransformerStack


@dataclass(frozen=True)
class BertConfig:
    vocab_size: int = 30522
    dim: int = 768
    num_layers: int = 12
    num_heads: int = 12
    hidden_dim: int = 3072
    max_len: int = 512
    type_vocab_size: int = 2
    dropout: float = 0.1
    layer_norm_eps: float = 1e-12
    # auto | flash | reference | ring | ulysses — ulysses is the natural
    # seq-parallel fit for BERT (bidirectional + padding masks; the mask
    # ships globally through the engine's extras channel)
    attn_impl: str = "auto"

    @classmethod
    def base(cls) -> "BertConfig":
        return cls()

    @classmethod
    def tiny(cls) -> "BertConfig":
        return cls(vocab_size=128, dim=32, num_layers=2, num_heads=2, hidden_dim=64, max_len=64)


class Bert(Module):
    def __init__(self, cfg: BertConfig = BertConfig()):
        super().__init__()
        self.cfg_obj = cfg
        self.child("tok_emb", Embedding(cfg.vocab_size, cfg.dim))
        self.child("pos_emb", Embedding(cfg.max_len, cfg.dim))
        self.child("type_emb", Embedding(cfg.type_vocab_size, cfg.dim))
        self.child("emb_norm", LayerNorm(cfg.dim, eps=cfg.layer_norm_eps))
        self.child("emb_drop", Dropout(cfg.dropout))
        self.child(
            "encoder",
            TransformerStack(
                cfg.num_layers,
                TransformerBlock,
                dim=cfg.dim,
                num_heads=cfg.num_heads,
                hidden_dim=cfg.hidden_dim,
                norm_style="post",
                norm="layer",
                norm_eps=cfg.layer_norm_eps,
                activation="gelu_exact",
                use_bias=True,
                dropout=cfg.dropout,
                attn_impl=cfg.attn_impl,
            ),
        )
        self.child("pooler", Dense(cfg.dim, cfg.dim))

    def apply(
        self,
        params,
        input_ids,
        *,
        token_type_ids=None,
        attention_mask=None,  # [B, T] 1=real token
        rng=None,
        train=False,
        **_,
    ):
        B, T = input_ids.shape
        pos = jnp.arange(T)[None, :]
        if token_type_ids is None:
            token_type_ids = jnp.zeros_like(input_ids)
        x = (
            self.children["tok_emb"].apply(params["tok_emb"], input_ids)
            + self.children["pos_emb"].apply(params["pos_emb"], pos)
            + self.children["type_emb"].apply(params["type_emb"], token_type_ids)
        )
        x = self.children["emb_norm"].apply(params["emb_norm"], x)
        r0, r1 = jax.random.split(rng) if rng is not None else (None, None)
        x = self.children["emb_drop"].apply(params["emb_drop"], x, rng=r0, train=train)

        mask = None
        if attention_mask is not None:
            mask = attention_mask[:, None, None, :].astype(bool)

        h = self.children["encoder"].apply(
            params["encoder"], x, mask=mask, rng=r1, train=train
        )
        pooled = jnp.tanh(self.children["pooler"].apply(params["pooler"], h[:, 0]))
        return {"last_hidden_state": h, "pooled": pooled}


def bert_pipeline_parts(model: "Bert", params: dict, num_classes_head=None):
    """Split a Bert (or BertClassifier param tree) into pipeline parts.
    If ``num_classes_head`` is given, params must be a BertClassifier tree
    and the head produces classification logits from [CLS]."""
    from tensorlink_tpu.parallel.engine import PipelineParts

    bert = model
    bp = params if num_classes_head is None else params["bert"]
    stack = bert.children["encoder"]
    block = stack.blocks()[0]

    emb_drop = bert.children["emb_drop"]

    def embed_fn(emb_params, batch, rng=None):
        ids = batch["input_ids"]
        T = ids.shape[1]
        pos = jnp.arange(T)[None, :]
        tt = batch.get("token_type_ids")
        tt = jnp.zeros_like(ids) if tt is None else tt
        x = (
            bert.children["tok_emb"].apply(emb_params["tok_emb"], ids)
            + bert.children["pos_emb"].apply(emb_params["pos_emb"], pos)
            + bert.children["type_emb"].apply(emb_params["type_emb"], tt)
        )
        x = bert.children["emb_norm"].apply(emb_params["emb_norm"], x)
        return emb_drop.apply({}, x, rng=rng, train=rng is not None)

    if num_classes_head is not None:
        from tensorlink_tpu.nn.layers import Dropout

        cls_drop = Dropout(bert.cfg_obj.dropout)

        def head_fn(all_params, x, batch, rng=None):
            pooled = jnp.tanh(
                bert.children["pooler"].apply(all_params["head"]["pooler"], x[:, 0])
            )
            pooled = cls_drop.apply({}, pooled, rng=rng, train=rng is not None)
            hw = all_params["head"]["cls"]
            return pooled @ hw["w"].astype(pooled.dtype) + hw["b"].astype(pooled.dtype)

        head_params = {"pooler": bp["pooler"], "cls": params["head"]}
    else:
        def head_fn(all_params, x, batch, rng=None):
            return x  # last_hidden_state

        # no pooler in the optimized tree: head_fn never uses it, and
        # decoupled weight decay would silently shrink unused params
        # (review finding)
        head_params = {}

    def extras_fn(batch):
        # global [B, 1, 1, T] key-padding mask, replicated to every stage
        # (and every seq shard — ring/ulysses slice it by global offset);
        # absent mask -> no extras, blocks run the dense path
        am = batch.get("attention_mask")
        if am is None:
            return None
        return {"mask": am[:, None, None, :].astype(bool)}

    def block_fn(blk_p, x, rng=None, extras=None):
        return block.apply(
            blk_p, x, mask=None if extras is None else extras["mask"],
            rng=rng, train=rng is not None,
        )

    return PipelineParts(
        embed_fn=embed_fn,
        block=block,
        block_params=bp["encoder"],
        block_fn=block_fn,
        extras_fn=extras_fn,
        # CLS pooling selects token 0 — NOT a uniform per-token
        # reduction, so 1F1B+seq>1 must reject it (engine guard); the
        # headless variant's reduction depends on the caller's loss_fn,
        # so it stays None (unknown)
        head_per_token=False if num_classes_head is not None else None,
        head_fn=head_fn,
        embed_params={
            "tok_emb": bp["tok_emb"],
            "pos_emb": bp["pos_emb"],
            "type_emb": bp["type_emb"],
            "emb_norm": bp["emb_norm"],
        },
        head_params=head_params,
    )


class BertClassifier(Module):
    """BertForSequenceClassification equivalent — the reference's e2e
    fine-tune workload (tests/ml/test_full_train.py:75)."""

    def __init__(self, cfg: BertConfig, num_classes: int):
        super().__init__()
        self.num_classes = num_classes
        self.child("bert", Bert(cfg))
        self.child("drop", Dropout(cfg.dropout))
        self.child("head", Dense(cfg.dim, num_classes))

    def apply(self, params, input_ids, *, attention_mask=None, rng=None, train=False, **kw):
        r0, r1 = jax.random.split(rng) if rng is not None else (None, None)
        out = self.children["bert"].apply(
            params["bert"],
            input_ids,
            attention_mask=attention_mask,
            rng=r0,
            train=train,
            **kw,
        )
        pooled = self.children["drop"].apply(params["drop"], out["pooled"], rng=r1, train=train)
        return self.children["head"].apply(params["head"], pooled)
