from tensorlink_tpu.models.mlp import MLP, MLPConfig  # noqa: F401
from tensorlink_tpu.models.bert import Bert, BertClassifier, BertConfig  # noqa: F401
from tensorlink_tpu.models.gpt2 import GPT2, GPT2Config  # noqa: F401
from tensorlink_tpu.models.vit import ViT, ViTClassifier, ViTConfig  # noqa: F401
from tensorlink_tpu.models.llama import Llama, LlamaConfig  # noqa: F401
from tensorlink_tpu.models.t5 import T5, T5Config  # noqa: F401
