from tensorlink_tpu.models.mlp import MLP, MLPConfig  # noqa: F401
