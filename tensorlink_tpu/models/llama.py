"""Llama 2/3 family, TPU-native (BASELINE.json config[4]: Llama-3-8B
sharded inference).

RMSNorm + RoPE + grouped-query attention + SwiGLU, no biases, untied LM
head — built from the framework's own blocks so TP `PartitionSpec`s
(Megatron col/row splits per block) and pipeline slicing apply unchanged.
Weights import from HF `LlamaForCausalLM` checkpoints via
models/hf_import.py; the reference would have shipped the whole module as
a pickle (src/p2p/torch_node.py:159-162).
"""

from __future__ import annotations

from dataclasses import dataclass

import jax.numpy as jnp

from tensorlink_tpu.nn.module import Module
from tensorlink_tpu.nn.layers import Dense, Embedding, RMSNorm
from tensorlink_tpu.nn.transformer import TransformerBlock, TransformerStack


@dataclass(frozen=True)
class LlamaConfig:
    vocab_size: int = 128256
    dim: int = 4096
    num_layers: int = 32
    num_heads: int = 32
    num_kv_heads: int = 8
    hidden_dim: int = 14336
    max_len: int = 8192
    rope_theta: float = 500000.0
    rms_eps: float = 1e-5
    attn_impl: str = "auto"  # auto | flash | reference | ring (seq-parallel)
    # Mixture-of-Experts FFN (Mixtral-style): 0 = dense. Experts shard
    # over the mesh 'model' axis (nn/moe.py — expert parallelism as
    # tensor sharding; see that module for the measured collective set).
    moe_experts: int = 0
    moe_top_k: int = 2
    moe_capacity_factor: float = 1.25
    # sliding-window attention (Mistral-style): each token attends the
    # last `attn_window` positions only. Supported by the reference impl
    # and the Pallas flash kernel (in-kernel band mask + whole-block
    # skip: O(T*window) long-seq cost); ring/ulysses reject it loudly.
    # None = full causal attention.
    attn_window: int | None = None
    # fused q/k/v projection (nn/attention.py qkv_fused): decode-perf
    # option; per-kv-group layout keeps TP head-aligned
    qkv_fused: bool = False

    @classmethod
    def llama3_8b(cls) -> "LlamaConfig":
        return cls()

    @classmethod
    def mistral_7b(cls) -> "LlamaConfig":
        """Mistral-7B-v0.1 shape: Llama trunk + 4096-token sliding
        window (the architecture's distinguishing feature)."""
        return cls(vocab_size=32000, dim=4096, num_layers=32,
                   num_heads=32, num_kv_heads=8, hidden_dim=14336,
                   max_len=32768, rope_theta=10000.0,
                   attn_window=4096)

    @classmethod
    def mixtral_8x7b(cls) -> "LlamaConfig":
        """Mixtral-8x7B shape: Llama-2-ish trunk, 8 experts, top-2."""
        return cls(vocab_size=32000, dim=4096, num_layers=32, num_heads=32,
                   num_kv_heads=8, hidden_dim=14336, max_len=32768,
                   rope_theta=1e6, moe_experts=8, moe_top_k=2)

    @classmethod
    def mistral_tiny(cls) -> "LlamaConfig":
        return cls(vocab_size=128, dim=32, num_layers=2, num_heads=4,
                   num_kv_heads=2, hidden_dim=64, max_len=64,
                   rope_theta=10000.0, attn_window=8)

    @classmethod
    def moe_tiny(cls) -> "LlamaConfig":
        return cls(vocab_size=128, dim=32, num_layers=2, num_heads=4,
                   num_kv_heads=2, hidden_dim=64, max_len=64,
                   rope_theta=10000.0, moe_experts=4, moe_top_k=2)

    @classmethod
    def llama3_70b(cls) -> "LlamaConfig":
        return cls(dim=8192, num_layers=80, num_heads=64, num_kv_heads=8,
                   hidden_dim=28672)

    @classmethod
    def llama2_7b(cls) -> "LlamaConfig":
        return cls(vocab_size=32000, dim=4096, num_layers=32, num_heads=32,
                   num_kv_heads=32, hidden_dim=11008, max_len=4096,
                   rope_theta=10000.0, rms_eps=1e-5)

    @classmethod
    def tiny(cls) -> "LlamaConfig":
        return cls(vocab_size=128, dim=32, num_layers=2, num_heads=4,
                   num_kv_heads=2, hidden_dim=64, max_len=64,
                   rope_theta=10000.0)


class Llama(Module):
    def __init__(self, cfg: LlamaConfig = LlamaConfig()):
        super().__init__()
        self.cfg_obj = cfg
        self.child("tok_emb", Embedding(cfg.vocab_size, cfg.dim))
        self.child(
            "blocks",
            TransformerStack(
                cfg.num_layers,
                TransformerBlock,
                dim=cfg.dim,
                num_heads=cfg.num_heads,
                num_kv_heads=cfg.num_kv_heads,
                hidden_dim=cfg.hidden_dim,
                norm_style="pre",
                norm="rms",
                norm_eps=cfg.rms_eps,
                activation="silu",
                use_bias=False,
                gated_mlp=True,
                causal=True,
                rope=True,
                rope_theta=cfg.rope_theta,
                dropout=0.0,
                attn_impl=cfg.attn_impl,
                moe_experts=cfg.moe_experts,
                moe_top_k=cfg.moe_top_k,
                moe_capacity_factor=cfg.moe_capacity_factor,
                attn_window=cfg.attn_window,
                qkv_fused=cfg.qkv_fused,
            ),
        )
        self.child("norm_f", RMSNorm(cfg.dim, eps=cfg.rms_eps))
        self.child("lm_head", Dense(cfg.dim, cfg.vocab_size, use_bias=False, shard="col"))

    def apply(
        self,
        params,
        input_ids,
        *,
        caches=None,
        positions=None,
        mask=None,
        rng=None,
        train=False,
        logits: bool = True,
        **_,
    ):
        x = self.children["tok_emb"].apply(params["tok_emb"], input_ids)
        blocks = self.children["blocks"]
        if caches is not None:
            attn_caches = [c["attn"] for c in caches]
            x, new_attn = blocks.apply(
                params["blocks"], x, mask=mask, caches=attn_caches,
                positions=positions, rng=rng, train=train,
            )
            new_caches = [{"attn": c} for c in new_attn]
        else:
            new_caches = None
            x = blocks.apply(
                params["blocks"], x, mask=mask, positions=positions,
                rng=rng, train=train,
            )
        x = self.children["norm_f"].apply(params["norm_f"], x)
        out = (
            self.children["lm_head"].apply(params["lm_head"], x) if logits else x
        )
        if caches is not None:
            return out, new_caches
        return out

    def apply_with_aux(
        self, params, input_ids, *, positions=None, mask=None, rng=None,
        train=False, **_,
    ):
        """-> (logits, aux): the summed MoE router load-balancing loss
        across blocks (0.0 for dense configs). Mixtral-style training
        adds ``aux_weight * aux`` to the task loss."""
        x = self.children["tok_emb"].apply(params["tok_emb"], input_ids)
        x, aux = self.children["blocks"].apply_with_aux(
            params["blocks"], x, mask=mask, positions=positions,
            rng=rng, train=train,
        )
        x = self.children["norm_f"].apply(params["norm_f"], x)
        return self.children["lm_head"].apply(params["lm_head"], x), aux

    def as_pipeline_parts(self, params):
        from tensorlink_tpu.parallel.engine import PipelineParts

        stack = self.children["blocks"]
        block = stack.blocks()[0]
        tok_emb = self.children["tok_emb"]
        norm_f, lm_head = self.children["norm_f"], self.children["lm_head"]

        def embed_fn(emb_params, batch, rng=None):
            return tok_emb.apply(emb_params["tok_emb"], batch["input_ids"])

        def head_fn(all_params, x, batch, rng=None):
            h = norm_f.apply(all_params["head"]["norm_f"], x)
            return lm_head.apply(all_params["head"]["lm_head"], h)

        return PipelineParts(
            embed_fn=embed_fn,
            block=block,
            block_params=params["blocks"],
            block_fn=lambda bp, x, rng=None: block.apply(
                bp, x, rng=rng, train=rng is not None
            ),
            head_fn=head_fn,
            embed_params={"tok_emb": params["tok_emb"]},
            head_params={"norm_f": params["norm_f"], "lm_head": params["lm_head"]},
            # MoE configs: the router's load-balancing loss rides the
            # pipeline when TrainConfig.moe_aux_weight > 0 (both schedules)
            block_fn_aux=(
                (lambda bp, x, rng=None: block.apply_with_aux(
                    bp, x, rng=rng, train=rng is not None))
                if self.cfg_obj.moe_experts else None
            ),
            # norm_f + lm_head CE reduces uniformly over tokens (1F1B can
            # run the head per token shard under seq sharding)
            head_per_token=True,
        )

    def init_caches(self, batch: int, max_len: int, dtype=jnp.bfloat16,
                    rolling: bool = False):
        stack = self.children["blocks"]
        return [
            {"attn": blk.children["attn"].init_cache(
                batch, max_len, dtype, rolling=rolling
            )}
            for blk in stack.blocks()
        ]
