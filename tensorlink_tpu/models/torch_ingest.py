"""Torch model ingestion: structural conversion to native modules.

The reference wraps a live ``torch.nn.Module`` and ships pickled subtrees
to workers (src/ml/distributed.py:305-378, src/p2p/torch_node.py:159-162).
Shipping torch code is impossible (and undesirable) TPU-side; the north
star is tracing torch -> XLA. The practical path (SURVEY §7.5.3) is:

1. **architecture re-implementation + weight import** for known families
   (models/hf_import.py covers BERT / GPT-2 / ViT / Llama), and
2. **structural conversion** — this module — for the long tail of
   container-style models: walk a ``torch.nn`` tree built from standard
   layers and emit the equivalent native `Sequential` + param pytree.
   The result partitions, ships, and jit-compiles like any native model
   (see tests/test_torch_ingest.py: ingested torch MLP -> request_job).

Supported leaves: Linear, ReLU, GELU, SiLU, Tanh, Sigmoid, LayerNorm,
Dropout, Embedding, Flatten, Identity, and nested Sequential. Anything
else raises with the module path — loud, not lossy.
"""

from __future__ import annotations

from typing import Any

import numpy as np

from tensorlink_tpu.nn.layers import Dense, Dropout, Embedding, LayerNorm
from tensorlink_tpu.nn.module import Lambda, Module, Sequential, _ACTIVATION_FNS


class UnsupportedTorchModule(ValueError):
    pass


def _act(name: str) -> Lambda:
    return Lambda(_ACTIVATION_FNS[name], name=name)


def _convert_leaf(mod: Any, path: str) -> tuple[Module, Any] | None:
    """-> (native module, params) or None to skip (e.g. Identity)."""
    import torch.nn as tn

    if isinstance(mod, tn.Linear):
        dense = Dense(mod.in_features, mod.out_features, use_bias=mod.bias is not None)
        p = {"w": np.asarray(mod.weight.detach().cpu()).T}
        if mod.bias is not None:
            p["b"] = np.asarray(mod.bias.detach().cpu())
        return dense, p
    if isinstance(mod, tn.Embedding):
        emb = Embedding(mod.num_embeddings, mod.embedding_dim)
        return emb, {"table": np.asarray(mod.weight.detach().cpu())}
    if isinstance(mod, tn.LayerNorm):
        if len(mod.normalized_shape) != 1:
            raise UnsupportedTorchModule(
                f"{path}: only last-dim LayerNorm supported"
            )
        if mod.weight is None or mod.bias is None:
            raise UnsupportedTorchModule(
                f"{path}: non-affine / bias-free LayerNorm not supported"
            )
        ln = LayerNorm(mod.normalized_shape[0], eps=mod.eps)
        return ln, {
            "scale": np.asarray(mod.weight.detach().cpu()),
            "bias": np.asarray(mod.bias.detach().cpu()),
        }
    if isinstance(mod, tn.Dropout):
        return Dropout(mod.p), {}
    if isinstance(mod, tn.ReLU):
        return _act("relu"), {}
    if isinstance(mod, tn.GELU):
        # torch GELU(approximate="none") is the erf form
        return _act("gelu" if mod.approximate == "tanh" else "gelu_exact"), {}
    if isinstance(mod, tn.SiLU):
        return _act("silu"), {}
    if isinstance(mod, tn.Tanh):
        return _act("tanh"), {}
    if isinstance(mod, tn.Sigmoid):
        return _act("sigmoid"), {}
    if isinstance(mod, tn.Flatten):
        if mod.start_dim != 1 or mod.end_dim != -1:
            raise UnsupportedTorchModule(f"{path}: only Flatten(1, -1)")
        # the registered fn, not an inline twin: workers rebuild Lambdas
        # from config BY NAME, and two definitions could drift
        return _act("flatten"), {}
    if isinstance(mod, tn.Identity):
        return None
    raise UnsupportedTorchModule(
        f"{path}: {type(mod).__name__} has no native equivalent; "
        "re-implement the architecture and import weights instead "
        "(models/hf_import.py pattern)"
    )


def from_torch(module: Any, path: str = "root") -> tuple[Sequential, dict]:
    """torch nn.Sequential (possibly nested) -> (native Sequential, params).

    Parameters come out as a flat {"0": ..., "1": ...} tree mirroring the
    native Sequential layout, ready for `partition_sequential` /
    `UserNode.request_job`.
    """
    import torch.nn as tn

    if not isinstance(module, tn.Sequential):
        # single leaf: wrap
        conv = _convert_leaf(module, path)
        if conv is None:
            return Sequential([]), {}
        mod, p = conv
        return Sequential([mod]), {"0": p}

    layers: list[Module] = []
    params: dict = {}
    for i, child in enumerate(module):
        cpath = f"{path}.{i}"
        if isinstance(child, tn.Sequential):
            sub, sub_p = from_torch(child, cpath)
            for j, l in enumerate(sub.layers):
                params[str(len(layers))] = sub_p[str(j)]
                layers.append(l)
            continue
        conv = _convert_leaf(child, cpath)
        if conv is None:
            continue
        mod, p = conv
        params[str(len(layers))] = p
        layers.append(mod)
    return Sequential(layers), params
