"""Torch model ingestion: structural conversion to native modules.

The reference wraps a live ``torch.nn.Module`` and ships pickled subtrees
to workers (src/ml/distributed.py:305-378, src/p2p/torch_node.py:159-162).
Shipping torch code is impossible (and undesirable) TPU-side; the north
star is tracing torch -> XLA. The practical path (SURVEY §7.5.3) is:

1. **architecture re-implementation + weight import** for known families
   (models/hf_import.py covers BERT / GPT-2 / ViT / Llama), and
2. **structural conversion** — this module — for the long tail of
   container-style models: walk a ``torch.nn`` tree built from standard
   layers and emit the equivalent native `Sequential` + param pytree.
   The result partitions, ships, and jit-compiles like any native model
   (see tests/test_torch_ingest.py: ingested torch MLP -> request_job).

Supported leaves: Linear, ReLU, GELU, SiLU, Tanh, Sigmoid, LayerNorm,
Dropout, Embedding, Flatten, Identity, nested Sequential, and — the
attention-bearing tier (VERDICT r4 next #9) — MultiheadAttention
(self-attention, batch_first), TransformerEncoderLayer, and
TransformerEncoder, which convert to the native MultiHeadAttention /
TransformerBlock with exact weight transposition (torch packs q/k/v in
one [3E, E] in_proj). Anything else raises with the module path — loud,
not lossy.
"""

from __future__ import annotations

from typing import Any

import numpy as np

from tensorlink_tpu.nn.layers import Dense, Dropout, Embedding, LayerNorm
from tensorlink_tpu.nn.module import Lambda, Module, Sequential, _ACTIVATION_FNS


class UnsupportedTorchModule(ValueError):
    pass


def _act(name: str) -> Lambda:
    return Lambda(_ACTIVATION_FNS[name], name=name)


def _convert_leaf(mod: Any, path: str) -> tuple[Module, Any] | None:
    """-> (native module, params) or None to skip (e.g. Identity)."""
    import torch.nn as tn

    if isinstance(mod, tn.Linear):
        dense = Dense(mod.in_features, mod.out_features, use_bias=mod.bias is not None)
        p = {"w": np.asarray(mod.weight.detach().cpu()).T}
        if mod.bias is not None:
            p["b"] = np.asarray(mod.bias.detach().cpu())
        return dense, p
    if isinstance(mod, tn.Embedding):
        emb = Embedding(mod.num_embeddings, mod.embedding_dim)
        return emb, {"table": np.asarray(mod.weight.detach().cpu())}
    if isinstance(mod, tn.LayerNorm):
        if len(mod.normalized_shape) != 1:
            raise UnsupportedTorchModule(
                f"{path}: only last-dim LayerNorm supported"
            )
        if mod.weight is None or mod.bias is None:
            raise UnsupportedTorchModule(
                f"{path}: non-affine / bias-free LayerNorm not supported"
            )
        ln = LayerNorm(mod.normalized_shape[0], eps=mod.eps)
        return ln, {
            "scale": np.asarray(mod.weight.detach().cpu()),
            "bias": np.asarray(mod.bias.detach().cpu()),
        }
    if isinstance(mod, tn.Dropout):
        return Dropout(mod.p), {}
    if isinstance(mod, tn.ReLU):
        return _act("relu"), {}
    if isinstance(mod, tn.GELU):
        # torch GELU(approximate="none") is the erf form
        return _act("gelu" if mod.approximate == "tanh" else "gelu_exact"), {}
    if isinstance(mod, tn.SiLU):
        return _act("silu"), {}
    if isinstance(mod, tn.Tanh):
        return _act("tanh"), {}
    if isinstance(mod, tn.Sigmoid):
        return _act("sigmoid"), {}
    if isinstance(mod, tn.Flatten):
        if mod.start_dim != 1 or mod.end_dim != -1:
            raise UnsupportedTorchModule(f"{path}: only Flatten(1, -1)")
        # the registered fn, not an inline twin: workers rebuild Lambdas
        # from config BY NAME, and two definitions could drift
        return _act("flatten"), {}
    if isinstance(mod, tn.Identity):
        return None
    if isinstance(mod, tn.MultiheadAttention):
        return _convert_mha(mod, path)
    if isinstance(mod, tn.TransformerEncoderLayer):
        return _convert_encoder_layer(mod, path)
    raise UnsupportedTorchModule(
        f"{path}: {type(mod).__name__} has no native equivalent; "
        "re-implement the architecture and import weights instead "
        "(models/hf_import.py pattern)"
    )


def _convert_mha(mod: Any, path: str,
                 allow_attn_dropout: bool = False) -> tuple[Module, dict]:
    """torch nn.MultiheadAttention (self-attention use) -> native
    MultiHeadAttention + params. torch packs q/k/v projections in one
    in_proj [3E, E] (row-major torch layout -> transpose to our
    [in, out]); out_proj maps back. batch_first=True required (native
    layout is [B, T, D]); attention-probability dropout is not
    implemented natively, so mod.dropout must be 0."""
    from tensorlink_tpu.nn.attention import MultiHeadAttention

    if not getattr(mod, "batch_first", False):
        raise UnsupportedTorchModule(
            f"{path}: MultiheadAttention needs batch_first=True "
            "(native layout is [B, T, D])"
        )
    if getattr(mod, "dropout", 0.0) and not allow_attn_dropout:
        # train-time semantic we cannot replicate; eval is identical.
        # TransformerEncoderLayer conversion opts in (its dropout= knob
        # fans into the MHA): there the block's residual dropout carries
        # the rate and attention-prob dropout is documented as dropped.
        raise UnsupportedTorchModule(
            f"{path}: attention-probability dropout is not supported "
            "natively; set MultiheadAttention(dropout=0)"
        )
    if mod.in_proj_weight is None:
        raise UnsupportedTorchModule(
            f"{path}: separate kdim/vdim projections not supported "
            "(self-attention with one packed in_proj only)"
        )
    if getattr(mod, "bias_k", None) is not None or getattr(
        mod, "add_zero_attn", False
    ):
        raise UnsupportedTorchModule(
            f"{path}: add_bias_kv / add_zero_attn have no native "
            "equivalent (their learned bias_k/bias_v and the zero "
            "column would be silently dropped)"
        )
    E = mod.embed_dim
    native = MultiHeadAttention(
        E, mod.num_heads, use_bias=mod.in_proj_bias is not None,
        causal=False, attn_impl="reference",
    )
    w = np.asarray(mod.in_proj_weight.detach().cpu())  # [3E, E]
    qw, kw, vw = w[:E], w[E : 2 * E], w[2 * E :]
    params = {
        "q": {"w": qw.T}, "k": {"w": kw.T}, "v": {"w": vw.T},
        "o": {"w": np.asarray(mod.out_proj.weight.detach().cpu()).T},
    }
    if mod.in_proj_bias is not None:
        b = np.asarray(mod.in_proj_bias.detach().cpu())
        params["q"]["b"], params["k"]["b"], params["v"]["b"] = (
            b[:E], b[E : 2 * E], b[2 * E :]
        )
        params["o"]["b"] = np.asarray(mod.out_proj.bias.detach().cpu())
    return native, params


def _convert_encoder_layer(mod: Any, path: str) -> tuple[Module, dict]:
    """torch nn.TransformerEncoderLayer -> native TransformerBlock.

    torch wiring (batch_first): self_attn -> dropout1 -> +residual ->
    norm1 -> linear1 -> act -> dropout -> linear2 -> dropout2 ->
    +residual -> norm2 (post-LN), or the norm_first pre-LN variant —
    exactly TransformerBlock's two styles with norm1=attn-side and
    norm2=mlp-side in both."""
    import torch.nn as tn

    from tensorlink_tpu.nn.transformer import TransformerBlock

    act_mod = getattr(mod, "activation", None)
    if callable(act_mod) and not isinstance(act_mod, tn.Module):
        import torch.nn.functional as F

        act = {F.relu: "relu", F.gelu: "gelu_exact"}.get(act_mod)
    else:
        if isinstance(act_mod, tn.GELU):
            # same approximate= mapping as the standalone GELU leaf
            act = "gelu" if act_mod.approximate == "tanh" else "gelu_exact"
        else:
            act = {tn.ReLU: "relu"}.get(type(act_mod))
    if act is None:
        raise UnsupportedTorchModule(
            f"{path}: unsupported encoder-layer activation {act_mod!r}"
        )
    _, attn_params = _convert_mha(
        mod.self_attn, f"{path}.self_attn", allow_attn_dropout=True
    )
    E = mod.self_attn.embed_dim
    H = mod.linear1.out_features
    block = TransformerBlock(
        dim=E,
        num_heads=mod.self_attn.num_heads,
        hidden_dim=H,
        norm_style="pre" if getattr(mod, "norm_first", False) else "post",
        norm="layer",
        norm_eps=mod.norm1.eps,
        activation=act,
        use_bias=mod.linear1.bias is not None,
        causal=False,
        dropout=float(mod.dropout1.p),
        attn_impl="reference",
    )
    params = {
        "norm1": {
            "scale": np.asarray(mod.norm1.weight.detach().cpu()),
            "bias": np.asarray(mod.norm1.bias.detach().cpu()),
        },
        "norm2": {
            "scale": np.asarray(mod.norm2.weight.detach().cpu()),
            "bias": np.asarray(mod.norm2.bias.detach().cpu()),
        },
        "attn": attn_params,
        "mlp": {
            "up": {"w": np.asarray(mod.linear1.weight.detach().cpu()).T},
            "down": {"w": np.asarray(mod.linear2.weight.detach().cpu()).T},
            "drop": {},
        },
        "drop": {},
    }
    if mod.linear1.bias is not None:
        params["mlp"]["up"]["b"] = np.asarray(mod.linear1.bias.detach().cpu())
        params["mlp"]["down"]["b"] = np.asarray(mod.linear2.bias.detach().cpu())
    return block, params


def from_torch(module: Any, path: str = "root") -> tuple[Sequential, dict]:
    """torch nn.Sequential (possibly nested) -> (native Sequential, params).

    Parameters come out as a flat {"0": ..., "1": ...} tree mirroring the
    native Sequential layout, ready for `partition_sequential` /
    `UserNode.request_job`.
    """
    import torch.nn as tn

    def expand(m):
        """Container -> child list, or None for leaves.
        TransformerEncoder is a chain of encoder layers (+ optional
        final norm) — structurally a Sequential."""
        if isinstance(m, tn.Sequential):
            return list(m)
        if isinstance(m, tn.TransformerEncoder):
            out = list(m.layers)
            if m.norm is not None:
                out.append(m.norm)
            return out
        return None

    top = expand(module)
    if top is None:
        # single leaf: wrap
        conv = _convert_leaf(module, path)
        if conv is None:
            return Sequential([]), {}
        mod, p = conv
        return Sequential([mod]), {"0": p}

    layers: list[Module] = []
    params: dict = {}
    for i, child in enumerate(top):
        cpath = f"{path}.{i}"
        if expand(child) is not None:
            sub, sub_p = from_torch(child, cpath)
            for j, l in enumerate(sub.layers):
                params[str(len(layers))] = sub_p[str(j)]
                layers.append(l)
            continue
        conv = _convert_leaf(child, cpath)
        if conv is None:
            continue
        mod, p = conv
        params[str(len(layers))] = p
        layers.append(mod)
    return Sequential(layers), params
