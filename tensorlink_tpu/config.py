"""Typed configuration for the whole framework.

The reference scatters configuration across `.env` keys read at import time,
a hardcoded contract-ABI path, constructor kwargs, and inline magic constants
(survey of src/p2p/smart_node.py:20-41, src/p2p/connection.py:39,
src/ml/distributed.py:16). Here all of it is a single tree of frozen
dataclasses with no import-time side effects; every subsystem takes its
config object explicitly.
"""

from __future__ import annotations

import dataclasses
import json
import os
from dataclasses import dataclass, field
from typing import Any, Mapping


@dataclass(frozen=True)
class MeshConfig:
    """Logical device mesh: axes (data, pipe, model, seq).

    The product of the axis sizes must equal the number of participating
    devices. ``pipe`` maps to pipeline stages (the TPU-native replacement for
    the reference's one-worker-per-submodule vertical partitioning,
    src/ml/distributed.py:305-378), ``data`` to data-parallel replicas
    (the reference's planned-but-unbuilt dp_factor, src/roles/user.py:161),
    ``model`` to tensor-parallel shards, ``seq`` to sequence/context
    parallelism (ring attention).
    """

    data: int = 1
    pipe: int = 1
    model: int = 1
    seq: int = 1

    AXIS_NAMES = ("data", "pipe", "model", "seq")

    @property
    def num_devices(self) -> int:
        return self.data * self.pipe * self.model * self.seq

    @property
    def shape(self) -> tuple[int, int, int, int]:
        return (self.data, self.pipe, self.model, self.seq)

    def axis_sizes(self) -> dict[str, int]:
        return dict(zip(self.AXIS_NAMES, self.shape))


@dataclass(frozen=True)
class DistributedConfig:
    """Multi-HOST mesh formation (SURVEY §2.4/§5.8: jax.distributed +
    gRPC coordination over DCN, the road to a v4-32-style pod slice).

    One SPMD program spans every process: each host contributes its
    local chips and `jax.distributed.initialize` joins them into one
    global device set, from which `make_mesh` builds the (data, pipe,
    model, seq) mesh. The reference's analogue is its whole multi-machine
    premise (socket workers, src/p2p/smart_node.py:490-537) — here the
    DATA plane is one compiled program and only job control rides the
    P2P overlay.

    ``coordinator`` is "host:port" of process 0. ``num_processes`` and
    ``process_id`` may be None when the platform supplies them (TPU pod
    metadata); on CPU/manual deployments set them explicitly.
    """

    coordinator: str | None = None  # None = single-process (no init)
    num_processes: int | None = None
    process_id: int | None = None
    # bound local devices per host (None = all; CPU tests use
    # xla_force_host_platform_device_count instead)
    local_device_ids: tuple | None = None

    @property
    def enabled(self) -> bool:
        return self.coordinator is not None


@dataclass(frozen=True)
class TrainConfig:
    """Training hyperparameters + micro-batching.

    ``micro_batches`` plays the role of the reference's
    batch_size // micro_batch_size thread count (src/ml/distributed.py:91),
    but here it is the static length of the pipeline schedule loop.
    """

    batch_size: int = 32
    micro_batches: int = 4
    learning_rate: float = 2e-5
    weight_decay: float = 0.0
    optimizer: str = "adamw"  # adam | adamw | sgd
    warmup_steps: int = 0
    total_steps: int = 1000
    schedule: str = "constant"  # constant | linear | cosine
    grad_clip_norm: float | None = 1.0
    seed: int = 0
    dtype: str = "bfloat16"  # compute dtype; params stay f32
    remat: bool = False  # jax.checkpoint each stage/block
    pp_schedule: str = "gpipe"  # gpipe | 1f1b (bounded-memory interleave)
    # weight of the MoE router load-balancing loss added to the task loss
    # (0 = off; requires PipelineParts.block_fn_aux; works under both
    # pipeline schedules)
    moe_aux_weight: float = 0.0
    # "lora" = train ONLY LoRA adapter leaves (nn/lora.py lora_init'd
    # params): base weights ride the same sharded update program with a
    # zero update, so every schedule/axis combination works unchanged
    train_only: str | None = None
    # FSDP/ZeRO-3: shard params + optimizer moments over the ``data``
    # axis as well (parallel/dp.py fsdp_spec_tree); XLA all-gathers at
    # use and reduce-scatters grads. Replicated DP otherwise.
    fsdp: bool = False
    # storage dtype of adam/adamw m+v ("bfloat16" halves optimizer-state
    # bytes and HBM traffic; update math stays f32 — train/optim.py)
    opt_moment_dtype: str = "float32"
    # non-finite sentinel (runtime/flight.py): Trainer._step always
    # reports a ``nonfinite`` flag in its stats; with this set, a step
    # whose loss/grads are non-finite leaves params, optimizer moments,
    # and the step counter UNCHANGED (the anomaly is still counted in
    # train_nonfinite_total and recorded as a flight event)
    skip_nonfinite_updates: bool = False

    def __post_init__(self):
        # validated HERE so BOTH trainers (train/trainer.py Trainer and
        # parallel/engine.py ShardedTrainer) reject a typo'd mode — a
        # silently ignored train_only would full-fine-tune a run the
        # user believes is frozen-base LoRA
        if self.train_only not in (None, "lora"):
            raise ValueError(
                f"unknown train_only {self.train_only!r}; supported: 'lora'"
            )
        from tensorlink_tpu.train.optim import SUPPORTED_MOMENT_DTYPES

        if self.opt_moment_dtype not in SUPPORTED_MOMENT_DTYPES:
            # same allowlist the P2P worker schema enforces — one source
            # of truth stops a local config from silently doing what a
            # remote job would reject (fp16's narrow exponent can
            # over/underflow the second moment)
            raise ValueError(
                f"unsupported opt_moment_dtype {self.opt_moment_dtype!r}; "
                f"supported: {SUPPORTED_MOMENT_DTYPES}"
            )

    @property
    def micro_batch_size(self) -> int:
        if self.batch_size % self.micro_batches:
            raise ValueError(
                f"batch_size={self.batch_size} not divisible by "
                f"micro_batches={self.micro_batches}"
            )
        return self.batch_size // self.micro_batches


@dataclass(frozen=True)
class NodeConfig:
    """Control-plane node identity + transport settings.

    Replaces the reference's SmartNode ctor kwargs + BASE_PORT scanning
    (src/p2p/smart_node.py:41,103-112,949-967).
    """

    role: str = "worker"  # user | worker | validator
    host: str = "127.0.0.1"
    port: int = 0  # 0 = OS-assigned
    base_port: int = 38751
    max_connections: int = 64
    handshake_timeout_s: float = 10.0
    connect_timeout_s: float = 5.0  # per-candidate dial bound (alt_hosts)
    request_timeout_s: float = 5.0
    dht_replication: int = 3
    dht_buckets: int = 256
    heartbeat_interval_s: float = 2.0
    heartbeat_miss_limit: int = 3
    compression: str = "zstd"  # none | zlib | zstd
    compression_min_bytes: int = 4096
    off_chain: bool = True  # in-memory Registry instead of web3
    # chain binding when off_chain=False (reference reads CONTRACT/CHAIN_URL
    # from .env at import time, src/p2p/smart_node.py:20-30; here they are
    # explicit typed config, no import-time side effects)
    chain_url: str | None = None  # EVM JSON-RPC endpoint
    chain_contract: str | None = None  # registry contract address
    chain_sender: str | None = None  # from-address for node-managed txs
    key_dir: str | None = None  # None = ephemeral in-memory identity
    http_status_port: int | None = None  # aiohttp status endpoint
    # TP width for loaded stages: 1 = single device, -1 = all local
    # devices, N>1 = first N local devices (every chip a worker, SURVEY
    # §7.2 — the stage is sharded by the module's own PartitionSpecs)
    stage_tp_devices: int = 1
    # periodic DHT persistence (reference: save_dht_state every 600 s,
    # src/p2p/smart_node.py:701-728); None disables
    dht_snapshot_path: str | None = None
    dht_snapshot_interval_s: float = 600.0
    # NAT traversal (reference: miniupnpc IGD mapping + upward port scan,
    # src/p2p/smart_node.py:787-816,949-967). Off by default: cluster and
    # public-IP nodes need no mapping; port=-1 requests the base_port scan.
    upnp: bool = False
    upnp_lease_s: int = 0  # 0 = indefinite mapping
    upnp_timeout_s: float = 3.0
    upnp_ssdp_addr: tuple = ("239.255.255.250", 1900)  # overridable in tests
    # cadence of the validator's cached-registry refresh (serves the
    # non-blocking is_validator_local gate on the event loop)
    registry_refresh_s: float = 30.0
    # health sentinel loop (runtime/flight.py): event-loop lag probe,
    # watchdog trip-edge checks, memory watermark gauges
    health_interval_s: float = 1.0
    # a placed job whose train_step has not COMPLETED within this
    # deadline flips the master's /healthz unhealthy (armed on the first
    # step, disarmed by DistributedJob.shutdown); None disables
    step_watchdog_s: float | None = 300.0
    # persistent XLA compilation cache (runtime/compile_cache.py): a
    # restarted node reloads its compiled serving/stage programs from
    # disk instead of re-paying XLA. None defers to the
    # TL_COMPILE_CACHE_DIR environment variable; both unset = off.
    compile_cache_dir: str | None = None
    # persistent autotune store (runtime/autotune.py): measured
    # flash-block overrides, prefill-bucket sets, and the adaptive-
    # speculation K prior reload beside the compile cache, so a
    # restart warm-starts the CONSTANTS as well as the kernels. None
    # defers to TL_AUTOTUNE_DIR; both unset = off.
    autotune_dir: str | None = None
    # capability microbench at WorkerNode start (runtime/profiling.py
    # measure_capability): peak matmul TFLOPs + HBM read GB/s, cached
    # in the autotune store under the chip-global key so restarts skip
    # the measurement; published at /metrics, /node, and on heartbeat
    # PONGs (the validator fleet table ROADMAP-1 placement consumes).
    # None = on unless the TL_CAPABILITY_BENCH=0 environment kill
    # switch is set (the test suite sets it: dozens of ephemeral
    # workers must not each pay the bench); True forces it regardless.
    capability_bench: bool | None = None
    # retained jax.profiler captures from GET /profile (None = parsed
    # and discarded per request)
    profile_dir: str | None = None
    # on-node ring-buffer time-series (runtime/timeseries.py): every
    # metric sampled at this cadence into the fixed-memory retention
    # tiers behind GET /history, heartbeat deltas, and GET /fleet.
    # False turns the sampler (and the heartbeat delta it feeds) off —
    # the toggle the observability-overhead bench flips.
    timeseries_enabled: bool = True
    timeseries_interval_s: float = 1.0
    # SLO alert rules (runtime/alerts.py): path to an slo.json rule
    # file; None = the default rule set (host-bound / kv-pressure /
    # heartbeat-stale, no latency targets)
    slo_path: str | None = None

    def __post_init__(self):
        # wire serialization (msgpack/json) round-trips tuples as lists;
        # normalize so config equality survives to_dict/from_dict
        object.__setattr__(self, "upnp_ssdp_addr", tuple(self.upnp_ssdp_addr))


@dataclass(frozen=True)
class FrameworkConfig:
    mesh: MeshConfig = field(default_factory=MeshConfig)
    train: TrainConfig = field(default_factory=TrainConfig)
    node: NodeConfig = field(default_factory=NodeConfig)
    distributed: DistributedConfig = field(default_factory=DistributedConfig)

    # ------------------------------------------------------------------
    # (De)serialization — configs travel inside job records on the wire.
    # ------------------------------------------------------------------
    def to_dict(self) -> dict[str, Any]:
        return dataclasses.asdict(self)

    @classmethod
    def from_dict(cls, d: Mapping[str, Any]) -> "FrameworkConfig":
        dist = dict(d.get("distributed", {}))
        if dist.get("local_device_ids") is not None:
            dist["local_device_ids"] = tuple(dist["local_device_ids"])
        return cls(
            mesh=MeshConfig(**d.get("mesh", {})),
            train=TrainConfig(**d.get("train", {})),
            node=NodeConfig(**d.get("node", {})),
            distributed=DistributedConfig(**dist),
        )

    def to_json(self) -> str:
        return json.dumps(self.to_dict(), sort_keys=True)

    @classmethod
    def from_json(cls, s: str) -> "FrameworkConfig":
        return cls.from_dict(json.loads(s))

    def replace(self, **kw: Any) -> "FrameworkConfig":
        return dataclasses.replace(self, **kw)


def config_from_env(env: Mapping[str, str] | None = None) -> FrameworkConfig:
    """Optional env-var overrides (explicit, never at import time)."""
    env = dict(os.environ if env is None else env)
    mesh = MeshConfig(
        data=int(env.get("TLTPU_MESH_DATA", 1)),
        pipe=int(env.get("TLTPU_MESH_PIPE", 1)),
        model=int(env.get("TLTPU_MESH_MODEL", 1)),
        seq=int(env.get("TLTPU_MESH_SEQ", 1)),
    )
    return FrameworkConfig(mesh=mesh)
