"""tldiag — cluster-wide diagnostics over the node status endpoints.

``python -m tensorlink_tpu.diag`` (console script: ``tldiag``) scrapes
``/healthz``, ``/metrics`` (JSON + Prometheus), ``/spans``, ``/events``,
and ``/node`` from a list of node status ports into ONE diagnostic
bundle, prints a cluster health table (dead/unhealthy nodes, stale
heartbeats, stragglers, anomaly counts), and diffs ``BENCH_r*.json``
pairs for step-time/throughput regressions:

    tldiag scrape 127.0.0.1:8080 worker-1:8080 -o bundle.json
    tldiag table bundle.json
    tldiag bench-diff BENCH_r04.json BENCH_r05.json --threshold 0.05
    tldiag manifest-diff hlo.manifest.json /tmp/new-manifest.json

``manifest-diff`` reviews a tlhlo (analysis/hlo.py) manifest
regeneration: per-program direction verdicts — memory/collective bytes
lower-better at a threshold, alias/donated pairs exact (a shrunk alias
count is always a regression: a dropped donation).

Dependency-free in itself (stdlib + asyncio sockets — the same
dependency posture as the StatusServer it scrapes) and never touches an
accelerator, so it runs on an operator laptop against a remote cluster.
The scraping API is async (``scrape_cluster``) so in-process tests can
drive it against live asyncio nodes without deadlocking the shared
event loop.
"""

from __future__ import annotations

import argparse
import asyncio
import json
import re
import sys
import time
from typing import Any

# every node serves these (http_status.py); /jobs exists only on
# validators, /kv only on paged serving nodes, /history and /fleet only
# when the time-series sampler is on — all fetched opportunistically
ROUTES = ("/healthz", "/metrics", "/metrics?format=prom", "/spans",
          "/events", "/node", "/jobs", "/history", "/kv", "/fleet",
          "/ledger")


# ------------------------------------------------------------- scraping
async def http_get(
    host: str, port: int, path: str, timeout: float = 5.0
) -> tuple[int, bytes]:
    """Minimal HTTP/1.1 GET -> (status, body). Raises OSError/timeout
    for unreachable targets — callers turn that into a DEAD row."""
    reader, writer = await asyncio.wait_for(
        asyncio.open_connection(host, port), timeout
    )
    try:
        writer.write(
            f"GET {path} HTTP/1.1\r\nHost: {host}\r\n"
            "Connection: close\r\n\r\n".encode()
        )
        await writer.drain()
        raw = await asyncio.wait_for(reader.read(-1), timeout)
    finally:
        writer.close()
    head, _, body = raw.partition(b"\r\n\r\n")
    parts = head.split()
    if len(parts) < 2 or not parts[1].isdigit():
        raise ConnectionError(f"malformed response from {host}:{port}")
    return int(parts[1]), body


def parse_target(target: str) -> tuple[str, int]:
    """'host:port' or bare 'port' (localhost)."""
    host, _, port = target.rpartition(":")
    return (host or "127.0.0.1"), int(port)


async def scrape_node(target: str, timeout: float = 5.0) -> dict[str, Any]:
    """All routes of one node -> {"target", "routes": {...}, "error"?}.
    A node that answers ANY route is alive; one that answers none is
    recorded with the connection error (the bundle must name dead nodes,
    not skip them)."""
    host, port = parse_target(target)
    out: dict[str, Any] = {"target": target, "routes": {}}
    for path in ROUTES:
        try:
            status, body = await http_get(host, port, path, timeout)
        except (OSError, asyncio.TimeoutError, ConnectionError) as e:
            out["routes"][path] = {"error": f"{type(e).__name__}: {e}"}
            if path == "/healthz":  # first route failing = probably dead
                out["error"] = f"{type(e).__name__}: {e}"
            continue
        rec: dict[str, Any] = {"status": status}
        if "format=prom" in path:
            rec["text"] = body.decode(errors="replace")
        else:
            try:
                rec["body"] = json.loads(body) if body else None
            except ValueError:
                rec["text"] = body.decode(errors="replace")[:2000]
        out["routes"][path] = rec
    if all("error" in r for r in out["routes"].values()):
        out["error"] = out.get("error") or "unreachable"
    return out


async def scrape_cluster(
    targets: list[str], timeout: float = 5.0
) -> dict[str, Any]:
    """One bundle over every target, scraped concurrently."""
    nodes = await asyncio.gather(
        *(scrape_node(t, timeout) for t in targets)
    )
    return {
        "collected_at": time.time(),
        "targets": list(targets),
        "nodes": list(nodes),
    }


# ------------------------------------------------------- health table
# anomaly counters surfaced per row (from each node's /metrics counters)
ANOMALY_COUNTERS = (
    "train_nonfinite_total",
    "peer_dropped_total",
    "dispatch_errors_total",
    "receipt_anomaly_total",
)


def _route_body(scrape: dict, path: str) -> Any:
    return (scrape.get("routes", {}).get(path) or {}).get("body")


def node_row(
    scrape: dict,
    stale_heartbeat_s: float = 30.0,
    skew_threshold: float = 1.5,
) -> dict[str, Any]:
    """One cluster-table row from one node's scrape."""
    row: dict[str, Any] = {
        "target": scrape.get("target"),
        "role": "?",
        "node_id": "?",
        "healthy": None,
        "reasons": "",
        "peers": None,
        "max_heartbeat_age_s": None,
        "skew": None,
        "anomalies": {},
        "error_events": 0,
        "kv_pool_pct": None,
        "spec_accept_pct": None,
        "mfu_pct": None,
        "bubble_pct": None,
        "flags": [],
    }
    if scrape.get("error"):
        row["flags"].append("DEAD")
        row["reasons"] = scrape["error"]
        return row
    hz = scrape.get("routes", {}).get("/healthz") or {}
    body = hz.get("body") or {}
    row["healthy"] = hz.get("status") == 200 and bool(body.get("ok", True))
    if not row["healthy"]:
        row["flags"].append("UNHEALTHY")
        row["reasons"] = "; ".join(
            f"{k}: {v}" for k, v in (body.get("reasons") or {}).items()
        )
    node = _route_body(scrape, "/node") or {}
    row["role"] = node.get("role", "?")
    # disaggregated serving: the ROLE column names the advertised leg
    # (worker/prefill, worker/decode, worker/colocated) straight from
    # the capability record, so the cluster table reads as a serving
    # topology, not just a process list
    serve_mode = (node.get("capability") or {}).get("serving_mode")
    if serve_mode:
        row["role"] = f"{row['role']}/{serve_mode}"
    # pipeline-sharded serving: a loaded stage names its slot in the
    # chain (worker/stage1/3) so the table reads as the pipeline's
    # actual topology — which stage lives where, at a glance
    pcap = node.get("capability") or {}
    if pcap.get("pipe_stage") is not None:
        row["role"] = (
            f"{node.get('role', '?')}/stage{pcap['pipe_stage']}"
            f"/{pcap.get('pipe_n_stages', '?')}"
        )
    row["node_id"] = str(node.get("node_id", "?"))[:16]
    peers = node.get("peers") or {}
    row["peers"] = len(peers)
    ages = [
        p.get("last_seen_age_s")
        for p in peers.values()
        if isinstance(p, dict) and p.get("last_seen_age_s") is not None
    ]
    if ages:
        row["max_heartbeat_age_s"] = round(max(ages), 1)
        if max(ages) > stale_heartbeat_s:
            row["flags"].append("STALE-HEARTBEAT")
    stragglers = node.get("stragglers") or {}
    skew = stragglers.get("skew")
    if skew is not None:
        row["skew"] = round(float(skew), 2)
        if float(skew) > skew_threshold:
            row["flags"].append(
                f"STRAGGLER(stage {stragglers.get('slowest_stage')})"
            )
    serving = node.get("serving") or {}
    pool = serving.get("pool") or {}
    util = pool.get("utilization")
    if util is not None:
        # paged-KV pool pressure (serving nodes): a pool near capacity
        # is the serving analogue of a stale heartbeat — admissions are
        # about to backpressure with PoolExhaustedError
        row["kv_pool_pct"] = round(float(util) * 100, 1)
        if float(util) >= 0.9:
            row["flags"].append(
                f"KV-PRESSURE({pool.get('blocks_in_use')}/"
                f"{pool.get('num_blocks')})"
            )
    spec = serving.get("spec") or {}
    healed = serving.get("spec_self_healed")
    if spec.get("proposed_total"):
        # speculative serving: pathological acceptance means the draft
        # (or n-gram lookup) is a bad match for this node's traffic —
        # every rejected token was a wasted draft step, and below ~0.3
        # the extra passes can cost more than the accepted tokens buy
        acc = float(spec.get("acceptance_rate") or 0.0)
        row["spec_accept_pct"] = round(acc * 100, 1)
        if acc < 0.3 and not healed:
            row["flags"].append(
                f"LOW-ACCEPT({spec.get('mode')},{acc:.2f})"
            )
    if healed:
        # the engine already acted on its own LOW-ACCEPT condition
        # (dropped draft -> n-gram -> non-spec, serving.py
        # _maybe_self_heal): the condition cleared without operator
        # action — advisory flag replaced by the record of the fix
        row["flags"].append(f"SELF-HEALED({healed.get('to')})")
    disagg = serving.get("disagg") or {}
    wire_s = disagg.get("wire_s_ewma")
    pre_s = disagg.get("prefill_s_ewma")
    if wire_s is not None and pre_s is not None and float(wire_s) > float(pre_s):
        # the DCN hop costs more than the prefill compute it ships:
        # this prefill worker is transfer-bound — bigger blocks, better
        # compression, or a closer decode peer would pay more than a
        # faster chip
        row["flags"].append(
            f"XFER-STALLED({float(wire_s):.3f}s>{float(pre_s):.3f}s)"
        )
    adm = serving.get("admission") or {}
    if adm.get("shed_total"):
        # SLO admission control is actively shedding (serving.py
        # OverloadedError): the total is CLIMBING when the last shed is
        # recent — a historical shed from yesterday's burst is history,
        # not a flag. Clients see typed 429s with the retry_after_s
        # this row's /node reports under serving.admission.
        age = adm.get("last_shed_age_s")
        if age is not None and float(age) < 60.0:
            row["flags"].append(f"SHEDDING({adm['shed_total']})")
    # device-time telemetry (PR 13): the node's CapabilityRecord (/node
    # "capability") or its serving scheduler's device_time attribution.
    # MFU% = best per-program MFU; BUBBLE% = host-gap fraction of the
    # device timeline — above 30% the chip is waiting on the HOST
    # (dispatch, scheduling, input pipeline), not on compute/bandwidth,
    # and more chip will not make that node faster
    cap = node.get("capability") or {}
    dt = serving.get("device_time") or {}
    progs = {**(cap.get("programs") or {}), **(dt.get("programs") or {})}
    mfus = [
        p.get("mfu") for p in progs.values()
        if isinstance(p, dict) and p.get("mfu") is not None
    ]
    # pipeline stages advertise their decode MFU and bubble fraction
    # as capability scalars (pipe_mfu / pipe_bubble_frac) — a stage
    # with a fat bubble is waiting on its NEIGHBOURS' activations, and
    # rebalancing the layer split (not more chip) is the fix
    if cap.get("pipe_mfu") is not None:
        mfus.append(cap["pipe_mfu"])
    if mfus:
        row["mfu_pct"] = round(max(mfus) * 100, 1)
    gap = dt.get(
        "host_gap_frac",
        cap.get("host_gap_frac", cap.get("pipe_bubble_frac")),
    )
    if gap is not None:
        row["bubble_pct"] = round(float(gap) * 100, 1)
        if float(gap) > 0.3:
            row["flags"].append(f"HOST-BOUND({float(gap):.2f})")
    alerts = node.get("alerts") or {}
    firing = (alerts.get("own") or []) + (alerts.get("fleet") or [])
    if firing:
        # SLO burn-rate alerting (runtime/alerts.py): the node itself
        # says which budgets are burning — name the worst offender
        worst = max(
            firing,
            key=lambda a: (a.get("severity") == "error", a.get("name", "")),
        )
        row["flags"].append(f"ALERTS({len(firing)}:{worst.get('name')})")
    metrics = _route_body(scrape, "/metrics") or {}
    counters = metrics.get("counters") or {}
    row["anomalies"] = {
        k: counters[k] for k in ANOMALY_COUNTERS if counters.get(k)
    }
    if row["anomalies"]:
        row["flags"].append("ANOMALIES")
    # receipt auditing (validator rows): a worker billing busy seconds
    # its own published roofline / wall window cannot explain is a
    # metering integrity failure — name the count, `tldiag ledger`
    # names the worker
    ledger = _route_body(scrape, "/ledger") or {}
    oc = (ledger.get("anomalies") or {}).get("overclaim")
    if oc:
        row["flags"].append(f"OVERCLAIM({oc})")
    events = (_route_body(scrape, "/events") or {}).get("events") or []
    row["error_events"] = sum(1 for e in events if e.get("severity") == "error")
    return row


def cluster_table(
    bundle: dict,
    stale_heartbeat_s: float = 30.0,
    skew_threshold: float = 1.5,
) -> list[dict[str, Any]]:
    return [
        node_row(s, stale_heartbeat_s, skew_threshold)
        for s in bundle.get("nodes", [])
    ]


def render_table(rows: list[dict[str, Any]]) -> str:
    cols = ("target", "role", "node_id", "healthy", "peers",
            "max_heartbeat_age_s", "skew", "kv_pool_pct",
            "spec_accept_pct", "mfu_pct", "bubble_pct", "error_events",
            "flags")
    titles = ("TARGET", "ROLE", "NODE", "OK", "PEERS", "HB-AGE",
              "SKEW", "KV%", "SPEC%", "MFU%", "BUBBLE%", "ERR-EVTS",
              "FLAGS")

    def cell(row: dict, col: str) -> str:
        v = row.get(col)
        if col == "flags":
            extra = ",".join(
                f"{k}={n}" for k, n in (row.get("anomalies") or {}).items()
            )
            return ",".join(v or []) + (f" [{extra}]" if extra else "") or "-"
        if v is None:
            return "-"
        return str(v)

    table = [titles] + [[cell(r, c) for c in cols] for r in rows]
    widths = [max(len(line[i]) for line in table) for i in range(len(cols))]
    lines = [
        "  ".join(c.ljust(w) for c, w in zip(line, widths)).rstrip()
        for line in table
    ]
    unhealthy = [
        r for r in rows if r["flags"] or r["healthy"] is False
    ]
    for r in unhealthy:
        if r.get("reasons"):
            lines.append(f"  !! {r['target']}: {r['reasons']}")
    return "\n".join(lines)


# ------------------------------------------------------- bench diffing
# key fragments that say which way "good" points; everything else is
# reported as a delta without a regression verdict
_HIGHER_BETTER = (
    "samples_per_sec", "tokens_per_sec", "mfu", "speedup", "throughput",
    "fraction_attained", "vs_baseline", "tick_over_dispatch",
    # continuous-vs-static serving ratio: 1.0 = parity, higher = the
    # scheduler beats the static batch
    "vs_static",
    # paged KV cache: prefix sharing served MORE prompt tokens from
    # resident blocks
    "hit_rate",
    # speculative decoding: more accepted draft tokens per target
    # weight pass / higher acceptance = more tokens per weight read
    # (the decode-roofline lever); vs_nonspec is spec-over-baseline
    "tokens_per_weight_pass", "acceptance_rate", "vs_nonspec",
    # adaptive speculation: the controller's wall-clock win over the
    # best hand-tuned static K on the same mixed workload (> 1.0 =
    # the measure->adapt loop pays)
    "vs_best_static",
    # device-time telemetry: model-bandwidth utilization and the
    # measured chip HBM bandwidth (capability_hbm_gbps) — more of
    # either is strictly better ("mfu" already matches above)
    "mbu", "gbps",
    # disaggregated serving: tokens/s of the split prefill/decode path
    # over the colocated baseline (1.0 = parity; the wire-byte TOTAL
    # stays deliberately directionless — payload size is a property of
    # the workload — but per-token wire bytes and the KV footprint
    # ratios are regression axes now that int8 pools exist to shrink
    # them, see _LOWER_BETTER_RE)
    "vs_colocated",
    # pipeline-sharded serving: chain tokens/s over the single-node
    # paged baseline on the same traffic (1.0 = parity; > 1.0 = the
    # in-flight microbatching hides the hop latency)
    "vs_single_node",
)
_LOWER_BETTER_RE = re.compile(
    r"(_s$|_s_per_call$|seconds|latency|bubble_frac|drop_fraction"
    # serving latency percentiles (TTFT/TPOT histograms) and the int8
    # quality KL: smaller is better even where the unit suffix differs
    r"|ttft|tpot|(^|_)kl(_|$)"
    # paged KV cache at fixed bench traffic: fewer blocks / lower pool
    # pressure / fewer re-prefilled tokens = the sharing is working
    r"|kv_blocks|kv_pool_utilization|prefilled_tokens|cow_copies"
    # ISSUE 20 (int8 KV blocks): at fixed traffic, a smaller paged-
    # over-contiguous footprint ratio and fewer wire bytes per token
    # are the quantization win (the _total wire key stays undirected —
    # it scales with workload). decode_mbu_* and the kernel-vs-xla
    # tokens/sec ratio ride the existing higher-better fragments.
    r"|kv_footprint|kv_wire_bytes_per_token"
    # speculation at fixed traffic: fewer n-gram misses = the lookup
    # is finding real recurrences
    r"|preempt|spec_fallback"
    # overload robustness (serving_under_load round): shed load and
    # missed deadlines at fixed offered traffic are pure degradation,
    # as is INTERACTIVE p99 growing over its uncontended baseline
    r"|shed_rate|shed_total|deadline_miss|p99_degradation"
    # device-time telemetry: host-gap (pipeline bubble) fraction and
    # the measured always-on timing overhead — both pure waste
    r"|host_gap|overhead_frac"
    # work-receipt auditing (runtime/ledger.py): flagged/rejected
    # receipts at fixed traffic are integrity failures, not volume
    r"|anomal)"
)


def _direction(key: str) -> str | None:
    k = key.lower()
    leaf = k.rsplit(".", 1)[-1]
    if leaf == "value" or any(t in k for t in _HIGHER_BETTER):
        return "higher"
    if _LOWER_BETTER_RE.search(leaf):
        return "lower"
    return None


def _flatten_numeric(d: Any, prefix: str = "") -> dict[str, float]:
    out: dict[str, float] = {}
    if isinstance(d, dict):
        for k, v in d.items():
            out.update(_flatten_numeric(v, f"{prefix}.{k}" if prefix else str(k)))
    elif isinstance(d, bool):
        pass  # bools are not measurements
    elif isinstance(d, (int, float)) and prefix:
        out[prefix] = float(d)
    return out


def _bench_payload(rec: dict) -> dict:
    """Committed BENCH_r*.json wraps the bench's JSON line under
    ``parsed`` (driver metadata around it); accept the wrapper, the raw
    bench output, and — when ``parsed`` is null — the bench line
    embedded in the captured ``tail`` text."""
    inner = rec.get("parsed")
    if isinstance(inner, dict):
        return inner
    tail = rec.get("tail")
    if isinstance(tail, str):
        for line in reversed(tail.strip().splitlines()):
            i = line.find('{"metric"')
            if i >= 0:
                try:
                    return json.loads(line[i:])
                except ValueError:
                    break  # front-truncated tail: unrecoverable
    return rec


def bench_diff(
    old: dict, new: dict, threshold: float = 0.05
) -> dict[str, Any]:
    """Per-key relative deltas between two bench records (BENCH_r*.json
    shape). A key regresses when it moved AGAINST its direction by more
    than ``threshold`` (5% default); direction-less keys only report.
    This is a report, never a failure — CI policy belongs to the
    caller."""
    a = _flatten_numeric(_bench_payload(old))
    b = _flatten_numeric(_bench_payload(new))
    keys: dict[str, Any] = {}
    regressions: list[str] = []
    improvements: list[str] = []
    for k in sorted(set(a) & set(b)):
        if a[k] == 0:
            continue  # no meaningful relative delta
        delta = (b[k] - a[k]) / abs(a[k])
        direction = _direction(k)
        rec = {
            "old": a[k],
            "new": b[k],
            "delta_frac": round(delta, 4),
            "direction": direction,
        }
        if direction is not None and abs(delta) > threshold:
            worse = delta < 0 if direction == "higher" else delta > 0
            rec["regression"] = worse
            (regressions if worse else improvements).append(k)
        keys[k] = rec
    return {
        "threshold": threshold,
        "keys": keys,
        "regressions": regressions,
        "improvements": improvements,
        "only_old": sorted(set(a) - set(b)),
        "only_new": sorted(set(b) - set(a)),
    }


def render_bench_diff(diff: dict) -> str:
    lines = [
        f"bench diff (threshold {diff['threshold']:.0%}): "
        f"{len(diff['regressions'])} regression(s), "
        f"{len(diff['improvements'])} improvement(s)"
    ]
    for k in diff["regressions"]:
        r = diff["keys"][k]
        lines.append(
            f"  REGRESSION {k}: {r['old']:g} -> {r['new']:g} "
            f"({r['delta_frac']:+.1%})"
        )
    for k in diff["improvements"]:
        r = diff["keys"][k]
        lines.append(
            f"  improved   {k}: {r['old']:g} -> {r['new']:g} "
            f"({r['delta_frac']:+.1%})"
        )
    return "\n".join(lines)


# ---------------------------------------------------- manifest diffing
# tlhlo's hlo.manifest.json (analysis/hlo.py) pins per-program compiled
# facts; this diff says which way each one MOVED between two manifests —
# the review tool for a --write-manifest regeneration ("what did my
# change do to the compiled programs?"). Memory and collective bytes
# are measurements (lower is better, judged at a threshold); alias /
# donated / program-set facts are EXACT — any change is a verdict, and
# a SHRUNK alias count is always a regression (a dropped donation).
_MANIFEST_LOWER_BETTER = (
    "temp_bytes", "argument_bytes", "output_bytes",
    "f32_dot", "f32_convert", "host_calls",
)


def _manifest_key_direction(key: str) -> str | None:
    leaf = key.rsplit(".", 1)[-1]
    if leaf in _MANIFEST_LOWER_BETTER or key.startswith("collectives."):
        return "lower"
    if leaf in ("alias", "donated"):
        return "exact"
    return None


def manifest_diff(
    old: dict, new: dict, threshold: float = 0.05
) -> dict[str, Any]:
    """Per-program, per-key direction verdicts between two tlhlo
    manifests. Byte measurements regress when they GROW by more than
    ``threshold``; exact keys regress on any unfavorable change
    (alias/donated shrinking); added/removed programs and collective
    kinds are always reported."""
    a = old.get("programs", {})
    b = new.get("programs", {})
    programs: dict[str, Any] = {}
    regressions: list[str] = []
    improvements: list[str] = []
    for name in sorted(set(a) & set(b)):
        fa = _flatten_numeric(a[name])
        fb = _flatten_numeric(b[name])
        keys: dict[str, Any] = {}
        # identity facts are STRINGS (invisible to the numeric flatten):
        # a dtype flip bfloat16->float32 silently switches TLH103 off
        # for that program, so any change here is always a verdict
        for sk in ("dtype", "group"):
            sa, sb = a[name].get(sk), b[name].get(sk)
            if isinstance(sa, str) and isinstance(sb, str) and sa != sb:
                keys[sk] = {
                    "old": sa, "new": sb, "direction": "exact",
                    "regression": True,
                }
                regressions.append(f"{name}.{sk}")
        for k in sorted(set(fa) | set(fb)):
            va, vb = fa.get(k), fb.get(k)
            direction = _manifest_key_direction(k)
            full = f"{name}.{k}"

            def _i(v):  # manifest values are counts/bytes: keep ints
                return int(v) if v is not None and v == int(v) else v

            rec: dict[str, Any] = {
                "old": _i(va), "new": _i(vb), "direction": direction,
            }
            if va is None or vb is None:
                # a collective kind appearing/disappearing IS the event
                rec["regression"] = worse = va is None
                (regressions if worse else improvements).append(full)
            elif direction == "exact":
                if va != vb:
                    rec["regression"] = worse = vb < va
                    (regressions if worse else improvements).append(full)
            elif direction == "lower":
                if va:
                    delta = (vb - va) / abs(va)
                    rec["delta_frac"] = round(delta, 4)
                    if abs(delta) > threshold:
                        rec["regression"] = worse = delta > 0
                        (regressions if worse else improvements).append(full)
                elif vb:
                    # growth from a ZERO pin (first f32 dot, first host
                    # call, first temp byte) is the highest-signal move
                    # these keys make — a relative threshold cannot see
                    # it, so it is always a verdict
                    rec["regression"] = True
                    regressions.append(full)
            keys[k] = rec
        programs[name] = keys
    return {
        "threshold": threshold,
        "programs": programs,
        "regressions": regressions,
        "improvements": improvements,
        "added": sorted(set(b) - set(a)),
        "removed": sorted(set(a) - set(b)),
    }


def render_manifest_diff(diff: dict) -> str:
    lines = [
        f"manifest diff (threshold {diff['threshold']:.0%}): "
        f"{len(diff['regressions'])} regression(s), "
        f"{len(diff['improvements'])} improvement(s), "
        f"{len(diff['added'])} added, {len(diff['removed'])} removed "
        f"program(s)"
    ]

    def _fmt(full: str, tag: str) -> str:
        name, _, key = full.partition(".")
        # program names and collectives.* keys both contain dots —
        # resplit against the program table, LONGEST prefix first
        for prog in sorted(diff["programs"], key=len, reverse=True):
            if full.startswith(prog + "."):
                name, key = prog, full[len(prog) + 1:]
                break
        r = diff["programs"][name][key]
        delta = (
            f" ({r['delta_frac']:+.1%})" if "delta_frac" in r else ""
        )
        return (
            f"  {tag} {name} {key}: {r['old']} -> {r['new']}{delta}"
        )

    for full in diff["regressions"]:
        lines.append(_fmt(full, "REGRESSION"))
    for full in diff["improvements"]:
        lines.append(_fmt(full, "improved  "))
    for name in diff["added"]:
        lines.append(f"  added      {name}")
    for name in diff["removed"]:
        lines.append(f"  removed    {name}")
    return "\n".join(lines)


def proto_manifest_diff(old: dict, new: dict) -> dict[str, Any]:
    """Rolling-upgrade verdicts between two tlproto proto.manifest.json
    files. The compatibility contract (analysis/proto.py TLP4xx): a
    frame or field removal, a value-kind change, an optional field
    turning required, a new required field, or a wire-version bump all
    BREAK mixed-version fleets — an old peer still sends (or bare-reads)
    the old shape. A new frame only needs its pin recorded; a new
    optional field is the one silent evolution the contract allows."""
    a = old.get("frames", {})
    b = new.get("frames", {})
    breaks: list[str] = []
    pins: list[str] = []
    ok: list[str] = []
    frames: dict[str, Any] = {}
    for name in sorted(set(a) - set(b)):
        breaks.append(f"{name}: frame removed")
    for name in sorted(set(b) - set(a)):
        pins.append(f"{name}: frame added")
    for name in sorted(set(a) & set(b)):
        fa = a[name].get("fields", {})
        fb = b[name].get("fields", {})
        verdicts: dict[str, str] = {}
        for f in sorted(set(fa) - set(fb)):
            verdicts[f] = "removed"
            breaks.append(f"{name}.{f}: field removed")
        for f in sorted(set(fb) - set(fa)):
            if fb[f].get("required"):
                verdicts[f] = "added-required"
                breaks.append(
                    f"{name}.{f}: new required field (old senders omit it)"
                )
            else:
                verdicts[f] = "added-optional"
                ok.append(f"{name}.{f}: optional field added")
        for f in sorted(set(fa) & set(fb)):
            ka, kb = fa[f].get("kind"), fb[f].get("kind")
            if ka != kb and "any" not in (ka, kb):
                verdicts[f] = f"kind {ka}->{kb}"
                breaks.append(f"{name}.{f}: kind changed {ka} -> {kb}")
            elif not fa[f].get("required") and fb[f].get("required"):
                verdicts[f] = "now-required"
                breaks.append(
                    f"{name}.{f}: optional field turned required"
                )
        if verdicts:
            frames[name] = verdicts
    va = old.get("versions", {})
    vb = new.get("versions", {})
    for k in sorted(set(va) | set(vb)):
        if va.get(k) == vb.get(k):
            continue
        if k not in va:
            # a version constant born WITH its frame family: no old
            # peer ever sent those frames, so there is nothing to
            # skew against — record the pin like a frame addition
            pins.append(f"version {k}: pinned at {vb.get(k)}")
        else:
            breaks.append(
                f"version {k}: {va.get(k)} -> {vb.get(k)}"
            )
    return {
        "breaks": breaks, "pins": pins, "ok": ok, "frames": frames,
        "compatible": not breaks,
    }


def render_proto_diff(diff: dict) -> str:
    lines = [
        f"proto diff: {len(diff['breaks'])} break(s), "
        f"{len(diff['pins'])} pin update(s), "
        f"{len(diff['ok'])} compatible change(s)"
    ]
    for item in diff["breaks"]:
        lines.append(f"  BREAK {item}")
    for item in diff["pins"]:
        lines.append(f"  pin   {item}")
    for item in diff["ok"]:
        lines.append(f"  ok    {item}")
    if diff["compatible"]:
        lines.append("  rolling upgrade: safe (additive-optional only)")
    else:
        lines.append(
            "  rolling upgrade: UNSAFE — drain the fleet or version-gate"
        )
    return "\n".join(lines)


def latest_bench_record(root: str) -> tuple[str, dict] | None:
    """Newest USABLE committed BENCH_r*.json under ``root`` (descending
    round order; a round whose payload has no headline value or recorded
    an error — failed run, truncated capture — is skipped so bench.py
    never diffs a real run against noise). Returns (filename, record)
    or None."""
    import os

    try:
        names = os.listdir(root)
    except OSError:
        return None
    rounds = sorted(
        (
            (int(m.group(1)), name)
            for name in names
            if (m := re.fullmatch(r"BENCH_r(\d+)\.json", name))
        ),
        reverse=True,
    )
    for _, name in rounds:
        try:
            with open(os.path.join(root, name)) as f:
                rec = json.load(f)
        except (OSError, ValueError):
            continue
        payload = _bench_payload(rec)
        if payload.get("value") and "error" not in payload:
            return name, rec
    return None


# ------------------------------------------------------- /profile pull
async def fetch_profile(
    target: str, ms: int = 200, timeout: float | None = None
) -> dict[str, Any]:
    """Trigger a bounded ``GET /profile?ms=N`` capture on one node and
    return its parsed payload (op_breakdown bundle). The HTTP timeout
    covers the capture duration plus slack; a 409 means another capture
    is already running there."""
    host, port = parse_target(target)
    status, body = await http_get(
        host, port, f"/profile?ms={int(ms)}",
        timeout or (ms / 1000.0 + 15.0),
    )
    try:
        payload = json.loads(body) if body else None
    except ValueError:
        payload = {"text": body.decode(errors="replace")[:2000]}
    return {"target": target, "status": status, "body": payload}


def merge_profile_into_bundle(path: str, rec: dict[str, Any]) -> None:
    """Attach a fetched /profile capture to a saved scrape bundle (the
    node entry matching the target gains a ``/profile`` route; a fresh
    bundle is created when the file does not exist)."""
    import os

    if os.path.exists(path):
        with open(path) as f:
            bundle = json.load(f)
    else:
        bundle = {"collected_at": time.time(),
                  "targets": [rec["target"]], "nodes": []}
    node = next(
        (n for n in bundle.get("nodes", [])
         if n.get("target") == rec["target"]),
        None,
    )
    if node is None:
        node = {"target": rec["target"], "routes": {}}
        bundle.setdefault("nodes", []).append(node)
    node.setdefault("routes", {})["/profile"] = {
        "status": rec["status"], "body": rec["body"],
    }
    with open(path, "w") as f:
        json.dump(bundle, f)


def render_profile(rec: dict[str, Any]) -> str:
    body = rec.get("body") or {}
    if rec.get("status") != 200:
        return (
            f"{rec['target']}: /profile -> HTTP {rec.get('status')} "
            f"({(body or {}).get('error', '?')})"
        )
    ob = body.get("op_breakdown") or {}
    lines = [
        f"{rec['target']}: {body.get('duration_ms')} ms capture, "
        f"{ob.get('total_s', 0.0):.4f}s device time"
    ]
    for cat, d in list((ob.get("categories") or {}).items())[:8]:
        lines.append(
            f"  {cat}: {d['s']:.4f}s ({d['fraction']:.1%}, {d['ops']} ops)"
        )
    if not ob.get("categories"):
        lines.append(
            "  (no hlo_category events — CPU captures carry none; "
            "this is a TPU instrument)"
        )
    if body.get("trace_dir"):
        lines.append(f"  raw capture retained at {body['trace_dir']}")
    return "\n".join(lines)


# ----------------------------------------------- fleet watch / history
_SPARK = "▁▂▃▄▅▆▇█"


def sparkline(values: list[float], width: int = 32) -> str:
    """Unicode sparkline over the LAST ``width`` points, scaled to the
    visible min/max (a flat series renders as a flat low bar)."""
    vs = [float(v) for v in values][-width:]
    if not vs:
        return ""
    lo, hi = min(vs), max(vs)
    span = hi - lo
    if span <= 0:
        return _SPARK[0] * len(vs)
    return "".join(
        _SPARK[min(len(_SPARK) - 1, int((v - lo) / span * len(_SPARK)))]
        for v in vs
    )


# the dashboard's default panel: one sparkline per series per frame
WATCH_SERIES = (
    "serving_ttft_s.p99", "serving_tpot_s.p99",
    "kv_pool_utilization", "serving_requests_total",
)


async def fetch_fleet_frame(
    target: str,
    series: tuple[str, ...] = WATCH_SERIES,
    window_s: float = 120.0,
    timeout: float = 5.0,
) -> dict[str, Any]:
    """One dashboard frame: the /fleet summary plus a rolled query per
    watched series (only those the fleet has actually seen)."""
    host, port = parse_target(target)
    frame: dict[str, Any] = {"target": target, "t": time.time()}
    status, body = await http_get(host, port, "/fleet", timeout)
    if status != 200:
        raise ConnectionError(f"/fleet -> HTTP {status}")
    summary = json.loads(body)
    frame["summary"] = summary
    known = set(summary.get("series") or [])
    since = time.time() - window_s
    frame["queries"] = {}
    for name in series:
        if name not in known:
            continue
        _, qbody = await http_get(
            host, port, f"/fleet?series={name}&since={since}", timeout
        )
        try:
            frame["queries"][name] = json.loads(qbody)
        except ValueError:
            continue
    return frame


def render_watch(frame: dict[str, Any]) -> str:
    """One ANSI-free dashboard frame (the caller adds clear-screen):
    fleet sparklines, per-node last values + KV residency, active
    alerts."""
    summary = frame.get("summary") or {}
    nodes = summary.get("nodes") or {}
    when = time.strftime("%H:%M:%S", time.localtime(frame.get("t")))
    lines = [
        f"tldiag watch {frame.get('target')}  {when}  "
        f"{len(nodes)} node(s) reporting"
    ]
    queries = frame.get("queries") or {}
    if queries:
        lines.append("")
        namew = max(len(n) for n in queries)
        for name, q in queries.items():
            pts = q.get("fleet") or []
            vals = [p[1] for p in pts]
            last = f"{vals[-1]:g}" if vals else "-"
            lines.append(
                f"  {name.ljust(namew)}  {sparkline(vals):32s}  {last}"
            )
    if nodes:
        lines.append("")
        lines.append(
            "  NODE              AGE-S   KV-OCC  FRAG    CHAINS  SERIES"
        )
        for nid, rec in sorted(nodes.items()):
            kv = rec.get("kv") or {}
            age = rec.get("last_seen_age_s")
            lines.append(
                "  {:<16s}  {:<6s}  {:<6s}  {:<6s}  {:<6s}  {}".format(
                    nid[:16],
                    "-" if age is None else f"{age:.1f}",
                    "-" if "occupancy" not in kv
                    else f"{kv['occupancy']:.2f}",
                    "-" if "fragmentation" not in kv
                    else f"{kv['fragmentation']:.2f}",
                    "-" if "chains" not in kv else str(kv["chains"]),
                    len(rec.get("series") or []),
                )
            )
    alerts = summary.get("alerts") or {}
    firing = (alerts.get("own") or []) + (alerts.get("fleet") or [])
    lines.append("")
    if firing:
        lines.append(f"  ACTIVE ALERTS ({len(firing)}):")
        for a in firing:
            lines.append(
                f"    [{a.get('severity', '?'):5s}] {a.get('name')}: "
                f"{a.get('detail', '')}"
            )
    else:
        lines.append("  no active alerts")
    return "\n".join(lines)


async def watch_loop(
    target: str,
    interval: float = 2.0,
    iterations: int | None = None,
    series: tuple[str, ...] = WATCH_SERIES,
    out=None,
) -> int:
    """Poll /fleet and redraw. A TTY gets an ANSI clear per frame; a
    pipe (or --once) gets plain frames, newline-separated — the same
    renderer, so tests and terminals see identical content."""
    out = out or sys.stdout
    live = iterations is None and out.isatty()
    n = 0
    while True:
        try:
            frame = await fetch_fleet_frame(target, series)
            text = render_watch(frame)
        except (OSError, ConnectionError, asyncio.TimeoutError, ValueError) as e:
            text = f"tldiag watch {target}: {type(e).__name__}: {e}"
        if live:
            out.write("\x1b[2J\x1b[H" + text + "\n")
        else:
            out.write(text + "\n")
        out.flush()
        n += 1
        if iterations is not None and n >= iterations:
            return 0
        await asyncio.sleep(interval)


async def fetch_history(
    target: str,
    series: str | None = None,
    since: float | None = None,
    step: float | None = None,
    timeout: float = 5.0,
) -> dict[str, Any]:
    """GET /history from one node: the series catalog when ``series``
    is None, else that series' ring contents."""
    host, port = parse_target(target)
    path = "/history"
    if series:
        path += f"?series={series}"
        if since is not None:
            path += f"&since={since}"
        if step is not None:
            path += f"&step={step}"
    status, body = await http_get(host, port, path, timeout)
    payload = json.loads(body) if body else {}
    if status != 200:
        raise ConnectionError(
            f"/history -> HTTP {status}: {payload.get('error', '?')}"
        )
    return payload


def render_history(payload: dict[str, Any]) -> str:
    if "points" not in payload:  # catalog form
        tiers = ", ".join(
            f"{s:g}s x {n}" for s, n in payload.get("tiers") or []
        )
        lines = [f"retention tiers: {tiers}"]
        lines += [f"  {name}" for name in payload.get("series") or []]
        return "\n".join(lines)
    pts = payload.get("points") or []
    lines = [
        f"{payload.get('series')} ({payload.get('kind')}, "
        f"step {payload.get('step'):g}s, {len(pts)} point(s))"
    ]
    vals = [p[1] for p in pts]
    if vals:
        lines.append(f"  {sparkline(vals, width=64)}")
    for t, v in pts:
        when = time.strftime("%H:%M:%S", time.localtime(t))
        lines.append(f"  {when}  {v:g}")
    return "\n".join(lines)


# ------------------------------------------------- work-receipt ledger
async def fetch_ledger(target: str, timeout: float = 5.0) -> dict[str, Any]:
    """GET /ledger from a validator: the receipt auditor's per-tenant /
    per-worker rollups and anomaly tallies (runtime/ledger.py)."""
    host, port = parse_target(target)
    status, body = await http_get(host, port, "/ledger", timeout)
    payload = json.loads(body) if body else {}
    if status != 200:
        raise ConnectionError(
            f"/ledger -> HTTP {status}: {payload.get('error', '?')} "
            "(only nodes carrying a ReceiptAuditor — validators — "
            "serve this route)"
        )
    return payload


def _ledger_table(rows: dict[str, dict], label: str) -> list[str]:
    head = (f"{label:<20} {'receipts':>8} {'prompt':>8} {'emitted':>8} "
            f"{'observed':>8} {'busy_s':>9} {'kv_blk_s':>9} "
            f"{'wire_kb':>8} {'anom':>5}")
    out = [head, "-" * len(head)]
    for key, r in sorted(
        rows.items(), key=lambda kv: -kv[1].get("emitted_tokens", 0)
    ):
        obs = r.get("observed_tokens")
        out.append(
            f"{key[:20]:<20} {r.get('receipts', 0):>8} "
            f"{r.get('prompt_tokens', 0):>8} "
            f"{r.get('emitted_tokens', 0):>8} "
            f"{obs if obs is not None else '-':>8} "
            f"{r.get('busy_s', 0.0):>9.3f} "
            f"{r.get('kv_block_s', 0.0):>9.1f} "
            f"{r.get('wire_bytes', 0) / 1024:>8.1f} "
            f"{r.get('anomalies', 0):>5}"
        )
    return out


def render_ledger(payload: dict[str, Any]) -> str:
    lines = [
        f"receipts: {payload.get('accepted_total', 0)} accepted, "
        f"{payload.get('rejected_total', 0)} rejected; "
        f"{payload.get('observed_tokens_total', 0)} user-observed "
        "token(s)"
    ]
    anomalies = payload.get("anomalies") or {}
    if anomalies:
        lines.append("anomalies: " + ", ".join(
            f"{k}={v}" for k, v in sorted(anomalies.items())
        ))
    tenants = payload.get("tenants") or {}
    if tenants:
        lines.append("")
        lines += _ledger_table(tenants, "tenant")
    workers = payload.get("workers") or {}
    if workers:
        lines.append("")
        lines += _ledger_table(workers, "worker")
        flagged = [
            (k, r["last_anomaly"]) for k, r in workers.items()
            if r.get("last_anomaly")
        ]
        for wid, why in flagged:
            lines.append(f"  !! {wid[:20]}: last anomaly {why}")
    if not tenants and not workers:
        lines.append("(no receipts ingested yet)")
    return "\n".join(lines)


# ------------------------------------------------------- SLO gate (CI)
async def check_nodes(
    targets: list[str],
    slo: dict | str | None = None,
    timeout: float = 5.0,
) -> dict[str, Any]:
    """Evaluate the SLO rule set against each node's served /history
    rings — the CI gate behind ``tldiag check``. A node is judged on
    ITS OWN recorded telemetry (scraped, rebuilt into a local store,
    evaluated at the node's newest sample time so operator/node clock
    skew cannot fake or mask a burn). Unreachable nodes and nodes
    without /history FAIL — a gate that cannot see is not passing."""
    from tensorlink_tpu.runtime.alerts import (
        AlertEngine, default_rules, load_rules,
    )
    from tensorlink_tpu.runtime.timeseries import TimeSeriesStore

    rules = load_rules(slo) if slo else default_rules()
    needed = set()
    for r in rules:
        for name in (r.series, r.numerator, r.denominator):
            if name:
                needed.add(name)
    out: dict[str, Any] = {"targets": list(targets), "nodes": {}, "firing": []}
    for target in targets:
        rec: dict[str, Any] = {"alerts": [], "error": None}
        out["nodes"][target] = rec
        try:
            catalog = await fetch_history(target, timeout=timeout)
            store = TimeSeriesStore()
            newest = None
            for name in sorted(needed & set(catalog.get("series") or [])):
                q = await fetch_history(target, series=name, timeout=timeout)
                kind = q.get("kind") or "gauge"
                for t, v in q.get("points") or []:
                    store.record(name, float(v), kind, now=float(t))
                    if newest is None or t > newest:
                        newest = t
            engine = AlertEngine(rules)
            alerts = engine.evaluate(store, now=newest)
            rec["alerts"] = alerts
            for a in alerts:
                out["firing"].append({**a, "target": target})
        except (OSError, ConnectionError, asyncio.TimeoutError, ValueError) as e:
            rec["error"] = f"{type(e).__name__}: {e}"
            out["firing"].append({
                "name": f"unreachable@{target}", "target": target,
                "severity": "error", "detail": rec["error"],
            })
    out["ok"] = not out["firing"]
    return out


def render_check(result: dict[str, Any], fmt: str = "text") -> str:
    """``--format github`` emits workflow-command annotations — one
    ``::error``/``::warning`` line per firing alert, which the Actions
    runner turns into PR annotations; plain text otherwise."""
    lines = []
    if fmt == "github":
        for a in result["firing"]:
            level = "error" if a.get("severity") == "error" else "warning"
            detail = str(a.get("detail", "")).replace("\n", " ")
            lines.append(
                f"::{level} title=SLO {a.get('name')} "
                f"({a.get('target')})::{detail}"
            )
        if result["ok"]:
            lines.append("::notice title=SLO check::all targets within SLO")
        return "\n".join(lines)
    for target, rec in result["nodes"].items():
        if rec.get("error"):
            lines.append(f"{target}: UNREACHABLE ({rec['error']})")
        elif rec["alerts"]:
            lines.append(f"{target}: {len(rec['alerts'])} alert(s) firing")
            for a in rec["alerts"]:
                lines.append(
                    f"  [{a.get('severity', '?'):5s}] {a.get('name')}: "
                    f"{a.get('detail', '')}"
                )
        else:
            lines.append(f"{target}: ok")
    lines.append("SLO check: " + ("PASS" if result["ok"] else "FAIL"))
    return "\n".join(lines)


# ------------------------------------------------------------------ CLI
def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(
        prog="tldiag",
        description="cluster diagnostics over node status endpoints",
    )
    sub = ap.add_subparsers(dest="cmd", required=True)
    sc = sub.add_parser("scrape", help="collect a diagnostic bundle")
    sc.add_argument("targets", nargs="+", metavar="HOST:PORT")
    sc.add_argument("-o", "--out", default=None,
                    help="write the full bundle JSON here")
    sc.add_argument("--timeout", type=float, default=5.0)
    sc.add_argument("--stale-heartbeat-s", type=float, default=30.0)
    sc.add_argument("--skew-threshold", type=float, default=1.5)
    tb = sub.add_parser("table", help="health table from a saved bundle")
    tb.add_argument("bundle", help="bundle JSON from `tldiag scrape -o`")
    tb.add_argument("--stale-heartbeat-s", type=float, default=30.0)
    tb.add_argument("--skew-threshold", type=float, default=1.5)
    bd = sub.add_parser(
        "bench-diff", help="flag regressions between two BENCH_r*.json"
    )
    bd.add_argument("old")
    bd.add_argument("new")
    bd.add_argument("--threshold", type=float, default=0.05,
                    help="relative delta beyond which a directional key "
                         "counts as moved (default 5%%)")
    bd.add_argument("--json", action="store_true", dest="as_json",
                    help="print the full diff as JSON")
    pf = sub.add_parser(
        "profile",
        help="trigger a bounded jax.profiler capture on one node "
             "(GET /profile?ms=N) and print the op breakdown",
    )
    pf.add_argument("target", metavar="HOST:PORT")
    pf.add_argument("--ms", type=int, default=200,
                    help="capture duration in milliseconds (server "
                         "clamps to its bound)")
    pf.add_argument("-o", "--out", default=None,
                    help="attach the capture to this bundle JSON "
                         "(created if missing)")
    pf.add_argument("--timeout", type=float, default=None)
    md = sub.add_parser(
        "manifest-diff",
        help="direction verdicts between two tlhlo hlo.manifest.json "
             "(memory/collective bytes lower-better, alias pairs exact)",
    )
    md.add_argument("old")
    md.add_argument("new")
    md.add_argument("--threshold", type=float, default=0.05,
                    help="relative growth beyond which a byte "
                         "measurement regresses (default 5%%)")
    md.add_argument("--json", action="store_true", dest="as_json",
                    help="print the full diff as JSON")
    pd = sub.add_parser(
        "proto-diff",
        help="rolling-upgrade verdicts between two tlproto "
             "proto.manifest.json (removals/kind changes break, "
             "additive-optional is safe); exit 1 on breaks",
    )
    pd.add_argument("old")
    pd.add_argument("new")
    pd.add_argument("--json", action="store_true", dest="as_json",
                    help="print the full diff as JSON")
    wa = sub.add_parser(
        "watch",
        help="live fleet dashboard: poll a validator's /fleet and "
             "redraw sparklines, KV residency, and active alerts",
    )
    wa.add_argument("target", metavar="HOST:PORT",
                    help="a node running the fleet rollup (validator)")
    wa.add_argument("--interval", type=float, default=2.0)
    wa.add_argument("--once", action="store_true",
                    help="print one frame and exit (CI / pipes)")
    wa.add_argument("--series", action="append", default=None,
                    metavar="NAME",
                    help="series to sparkline (repeatable; default: "
                         "TTFT/TPOT p99, KV utilization, request rate)")
    hi = sub.add_parser(
        "history",
        help="one node's on-board ring buffers (GET /history): the "
             "series catalog, or one series' retained points",
    )
    hi.add_argument("target", metavar="HOST:PORT")
    hi.add_argument("--series", default=None, metavar="NAME")
    hi.add_argument("--since", type=float, default=None,
                    help="unix time lower bound (default: whole ring)")
    hi.add_argument("--step", type=float, default=None,
                    help="preferred bucket seconds (picks the tier)")
    hi.add_argument("--json", action="store_true", dest="as_json")
    lg = sub.add_parser(
        "ledger",
        help="per-tenant / per-worker metering rollups from a "
             "validator's receipt auditor (GET /ledger)",
    )
    lg.add_argument("target", metavar="HOST:PORT",
                    help="a node carrying a ReceiptAuditor (validator)")
    lg.add_argument("--json", action="store_true", dest="as_json")
    lg.add_argument("--timeout", type=float, default=5.0)
    ck = sub.add_parser(
        "check",
        help="SLO gate: evaluate alert rules against each node's "
             "/history rings; exit 1 if any alert fires",
    )
    ck.add_argument("targets", nargs="+", metavar="HOST:PORT")
    ck.add_argument("--slo", default=None,
                    help="SLO rule file (runtime/alerts.py compact or "
                         "explicit form); default rule set if omitted")
    ck.add_argument("--format", choices=("text", "github"),
                    default="text",
                    help="github: ::error/::warning workflow-command "
                         "annotations for Actions")
    ck.add_argument("--timeout", type=float, default=5.0)
    args = ap.parse_args(argv)

    if args.cmd == "scrape":
        bundle = asyncio.run(scrape_cluster(args.targets, args.timeout))
        if args.out:
            with open(args.out, "w") as f:
                json.dump(bundle, f)
            print(f"bundle written: {args.out}", file=sys.stderr)
        rows = cluster_table(
            bundle, args.stale_heartbeat_s, args.skew_threshold
        )
        print(render_table(rows))
        return 0
    if args.cmd == "table":
        with open(args.bundle) as f:
            bundle = json.load(f)
        print(render_table(cluster_table(
            bundle, args.stale_heartbeat_s, args.skew_threshold
        )))
        return 0
    if args.cmd == "bench-diff":
        with open(args.old) as f:
            old = json.load(f)
        with open(args.new) as f:
            new = json.load(f)
        diff = bench_diff(old, new, args.threshold)
        print(json.dumps(diff) if args.as_json else render_bench_diff(diff))
        return 0
    if args.cmd == "profile":
        rec = asyncio.run(fetch_profile(args.target, args.ms, args.timeout))
        if args.out:
            merge_profile_into_bundle(args.out, rec)
            print(f"capture attached to: {args.out}", file=sys.stderr)
        print(render_profile(rec))
        return 0 if rec.get("status") == 200 else 1
    if args.cmd == "manifest-diff":
        with open(args.old) as f:
            old = json.load(f)
        with open(args.new) as f:
            new = json.load(f)
        diff = manifest_diff(old, new, args.threshold)
        print(
            json.dumps(diff) if args.as_json
            else render_manifest_diff(diff)
        )
        return 0
    if args.cmd == "proto-diff":
        with open(args.old) as f:
            old = json.load(f)
        with open(args.new) as f:
            new = json.load(f)
        diff = proto_manifest_diff(old, new)
        print(
            json.dumps(diff) if args.as_json else render_proto_diff(diff)
        )
        return 0 if diff["compatible"] else 1
    if args.cmd == "watch":
        series = tuple(args.series) if args.series else WATCH_SERIES
        try:
            return asyncio.run(watch_loop(
                args.target, args.interval,
                iterations=1 if args.once else None, series=series,
            ))
        except KeyboardInterrupt:
            return 0
    if args.cmd == "history":
        try:
            payload = asyncio.run(fetch_history(
                args.target, args.series, args.since, args.step,
            ))
        except (OSError, ConnectionError, asyncio.TimeoutError) as e:
            print(f"{args.target}: {e}", file=sys.stderr)
            return 1
        print(json.dumps(payload) if args.as_json
              else render_history(payload))
        return 0
    if args.cmd == "ledger":
        try:
            payload = asyncio.run(fetch_ledger(args.target, args.timeout))
        except (OSError, ConnectionError, asyncio.TimeoutError) as e:
            print(f"{args.target}: {e}", file=sys.stderr)
            return 1
        print(json.dumps(payload) if args.as_json
              else render_ledger(payload))
        return 0
    if args.cmd == "check":
        result = asyncio.run(check_nodes(
            args.targets, args.slo, timeout=args.timeout,
        ))
        print(render_check(result, args.format))
        return 0 if result["ok"] else 1
    return 2  # pragma: no cover — argparse enforces the subcommands


if __name__ == "__main__":
    sys.exit(main())
