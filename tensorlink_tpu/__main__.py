"""CLI: `python -m tensorlink_tpu <command>`.

The reference ships per-role launch scripts with hardcoded keys and ports
(tests/run/test_worker.py etc.) and no CLI (survey §5.6). Here one typed
entry point launches any role, shows device info, or runs the demo:

    python -m tensorlink_tpu worker --port 38751 --http-port 8080
    python -m tensorlink_tpu validator --port 38752
    python -m tensorlink_tpu demo            # in-process e2e training job
    python -m tensorlink_tpu info            # devices + mesh capacity
"""

from __future__ import annotations

import argparse
import asyncio
import json
import sys


def _node_cfg(args, role: str):
    from tensorlink_tpu.config import NodeConfig

    return NodeConfig(
        role=role,
        host=args.host,
        port=args.port,
        key_dir=args.key_dir,
        http_status_port=args.http_port,
        stage_tp_devices=getattr(args, "stage_tp_devices", 1),
        dht_snapshot_path=args.dht_snapshot,
        upnp=args.upnp,
        off_chain=not getattr(args, "chain_url", None),
        chain_url=getattr(args, "chain_url", None),
        chain_contract=getattr(args, "chain_contract", None),
        chain_sender=getattr(args, "chain_sender", None),
    )


def _add_node_args(p: argparse.ArgumentParser) -> None:
    # loopback by default: the status endpoint is unauthenticated, so
    # exposing it network-wide must be an explicit operator choice
    p.add_argument("--host", default="127.0.0.1",
                   help="bind address (0.0.0.0 to serve the network)")
    p.add_argument("--port", type=int, default=0,
                   help="0 = OS-assigned; -1 = scan upward from base port")
    p.add_argument("--upnp", action="store_true",
                   help="map the listen port through the home router (UPnP "
                        "IGD) for NAT'd peers")
    p.add_argument("--http-port", type=int, default=None,
                   help="HTTP status endpoint port (off when omitted)")
    p.add_argument("--key-dir", default=None,
                   help="persistent identity dir (ephemeral when omitted)")
    p.add_argument("--bootstrap", default=None, metavar="HOST:PORT",
                   help="validator to join via (overrides the registry "
                        "auto-join when --chain-url is also given)")
    p.add_argument("--chain-url", default=None,
                   help="EVM JSON-RPC endpoint: validators register on "
                        "the contract; workers/users auto-join by "
                        "sampling it (no --bootstrap needed)")
    p.add_argument("--chain-contract", default=None,
                   help="registry contract address (0x...)")
    p.add_argument("--chain-sender", default=None,
                   help="from-address for node-managed transactions")
    p.add_argument("--dht-snapshot", default=None, metavar="PATH",
                   help="persist DHT state to PATH periodically (and "
                        "restore from it on start)")
    p.add_argument("--postmortem-dir", default=None, metavar="DIR",
                   help="write a post-mortem JSON bundle (flight events, "
                        "spans, metrics, config) into DIR on unhandled "
                        "crash or SIGTERM")
    # multi-HOST mesh formation (SURVEY §2.4/§5.8): all processes of one
    # slice join a single JAX runtime; jax.devices() then spans hosts and
    # ShardedTrainer programs compile over the global mesh
    p.add_argument("--coordinator", default=None, metavar="HOST:PORT",
                   help="jax.distributed coordinator (process 0's "
                        "address); omit for single-host")
    p.add_argument("--num-processes", type=int, default=None,
                   help="total processes in the multi-host slice "
                        "(TPU pods can infer this; set explicitly on CPU)")
    p.add_argument("--process-id", type=int, default=None,
                   help="this process's index in the slice")


def _maybe_init_distributed(args) -> None:
    if not getattr(args, "coordinator", None):
        return
    from tensorlink_tpu.config import DistributedConfig
    from tensorlink_tpu.runtime.mesh import initialize_distributed

    info = initialize_distributed(DistributedConfig(
        coordinator=args.coordinator,
        num_processes=args.num_processes,
        process_id=args.process_id,
    ))
    print(f"joined multi-host runtime: process {info['process_id']}/"
          f"{info['num_processes']}, {info['global_devices']} global / "
          f"{info['local_devices']} local devices")


async def _run_role(role: str, args) -> None:
    from tensorlink_tpu.roles.registry import InMemoryRegistry
    from tensorlink_tpu.roles.user import UserNode
    from tensorlink_tpu.roles.validator import ValidatorNode
    from tensorlink_tpu.roles.worker import WorkerNode

    _maybe_init_distributed(args)
    cls = {"worker": WorkerNode, "validator": ValidatorNode, "user": UserNode}[role]
    kw = {}
    if role == "validator" and not getattr(args, "chain_url", None):
        kw["registry"] = InMemoryRegistry()
    # chain-backed registry is built by ValidatorNode from cfg.chain_* when
    # off_chain=False (set in _node_cfg from --chain-url/--chain-contract)
    node = cls(_node_cfg(args, role), **kw)
    await node.start()
    if args.postmortem_dir:
        # black box: unhandled crash / SIGTERM dumps events + spans +
        # metrics + config + versions as one JSON bundle
        from tensorlink_tpu.runtime.flight import install_crash_handler

        install_crash_handler(
            args.postmortem_dir, recorder=node.flight, tracer=node.tracer,
            metrics=node.metrics, config=node.cfg,
        )
    validator_peer = None
    if args.bootstrap:
        host, port = args.bootstrap.rsplit(":", 1)
        validator_peer = await node.connect(host, int(port))
    elif role != "validator" and args.chain_url:
        # registry auto-join: sample validators from the contract and
        # dial (reference smart_node.py:539-585) — --chain-url suffices
        from tensorlink_tpu.chain import Web3Registry

        validator_peer = await node.bootstrap_from_registry(
            Web3Registry(args.chain_url, args.chain_contract)
        )
        if validator_peer is None:
            print("registry bootstrap found no reachable validator; "
                  "running unconnected (will accept inbound peers)")
    node.start_heartbeat()
    if role == "user" and getattr(args, "resume_dir", None):
        if validator_peer is None:
            raise SystemExit("--resume-dir requires --bootstrap validator")
        job = await node.resume_job_from_checkpoint(
            args.resume_dir, validator_peer
        )
        print(f"resumed job {job.job.job_id[:16]} at step {job.step}")
    print(f"{role} {node.node_id[:16]} listening on {args.host}:{node.port}"
          + (f", status :{node._http.bound_port}" if node._http else ""))
    try:
        await asyncio.Event().wait()  # run until interrupted
    finally:
        await node.stop()


def _cmd_info() -> int:
    import jax

    from tensorlink_tpu.runtime.mesh import local_device_info

    print(json.dumps(
        {
            "backend": jax.default_backend(),
            "device_count": jax.device_count(),
            "devices": local_device_info(),
        },
        indent=2, default=str,
    ))
    return 0


async def _cmd_demo() -> int:
    """Minimum end-to-end slice (SURVEY §7.4) in one process."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from tensorlink_tpu.config import NodeConfig
    from tensorlink_tpu.models.mlp import MLP, MLPConfig
    from tensorlink_tpu.roles.registry import InMemoryRegistry
    from tensorlink_tpu.roles.user import UserNode
    from tensorlink_tpu.roles.validator import ValidatorNode
    from tensorlink_tpu.roles.worker import WorkerNode

    def cfg(role):
        return NodeConfig(role=role, host="127.0.0.1", port=0)

    # warm up jax BEFORE wiring nodes: the first device compile can block
    # this single shared event loop long enough to expire the accept-side
    # handshake timer of an in-flight connection (all roles share one loop
    # here; separate processes in production)
    m = MLP(MLPConfig(in_dim=16, hidden_dim=32, out_dim=4, num_layers=2))
    p = m.init(jax.random.key(0))

    reg = InMemoryRegistry()
    validator = ValidatorNode(cfg("validator"), registry=reg)
    await validator.start()
    workers = []
    for _ in range(2):
        w = WorkerNode(cfg("worker"))
        await w.start()
        await w.connect("127.0.0.1", validator.port)
        workers.append(w)
    user = UserNode(cfg("user"))
    await user.start()
    v_peer = await user.connect("127.0.0.1", validator.port)

    job = await user.request_job(
        m.seq, p["seq"], v_peer, max_stage_bytes=16 * 32 * 4 + 200,
        micro_batches=2, train={"optimizer": "sgd", "learning_rate": 0.05},
    )
    print(f"job {job.job.job_id[:16]} placed on "
          f"{[st.peer.node_id[:8] for st in job.stages]}")

    rng = np.random.default_rng(0)
    x = rng.normal(size=(32, 16)).astype(np.float32)
    w_true = rng.normal(size=(16, 4))
    y = np.argmax(x @ w_true, -1)

    def loss_grad(logits, micro):
        lj = jnp.asarray(logits)
        yj = jnp.asarray(np.array_split(y, 2)[micro])

        def f(l):
            logz = jax.nn.logsumexp(l, axis=-1)
            ll = jnp.take_along_axis(l, yj[:, None], axis=-1)[..., 0]
            return jnp.mean(logz - ll)

        val, g = jax.value_and_grad(f)(lj)
        return float(val), np.asarray(g)

    for i in range(10):
        loss = await job.train_step(x, loss_grad)
        print(f"step {i}: loss {loss:.4f}")
    for n in (user, validator, *workers):
        await n.stop()
    print("demo OK")
    return 0


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(prog="tensorlink_tpu")
    sub = ap.add_subparsers(dest="cmd", required=True)
    for role in ("worker", "validator", "user"):
        sp = sub.add_parser(role, help=f"run a {role} node")
        _add_node_args(sp)  # includes --chain-* (validator: registry
        # membership; worker/user: contract auto-join)
        if role == "worker":
            sp.add_argument(
                "--stage-tp-devices", type=int, default=1,
                dest="stage_tp_devices",
                help="TP width for loaded stages (-1 = all local devices)",
            )
        if role == "user":
            sp.add_argument(
                "--resume-dir", default=None,
                help="resume a job from a durable checkpoint directory "
                     "(requires --bootstrap validator)",
            )
    sub.add_parser("info", help="local devices and capacity")
    sub.add_parser("demo", help="in-process end-to-end training demo")
    sub.add_parser("bench", help="run the repo benchmark (prints one JSON line)")
    kp = sub.add_parser(
        "keygen",
        help="pre-generate per-role RSA identities (the reference does this "
             "in a pip-install hook, config/custom_install.py:6-14; here it "
             "is an explicit command since PEP 517 builds can't run code)",
    )
    kp.add_argument("--key-dir", required=True, help="directory for the keys")
    kp.add_argument("--roles", default="worker,validator,user",
                    help="comma-separated roles to generate keys for")
    args = ap.parse_args(argv)

    if args.cmd == "keygen":
        from tensorlink_tpu.p2p.crypto import Identity

        for role in args.roles.split(","):
            ident = Identity.load_or_generate(args.key_dir, role.strip())
            print(f"{role.strip()}: {ident.node_id}")
        return 0
    if args.cmd == "info":
        return _cmd_info()
    if args.cmd == "demo":
        return asyncio.run(_cmd_demo())
    if args.cmd == "bench":
        import runpy
        import os

        sys.argv = ["bench.py"]
        runpy.run_path(
            os.path.join(os.path.dirname(os.path.dirname(
                os.path.abspath(__file__))), "bench.py"),
            run_name="__main__",
        )
        return 0
    try:
        asyncio.run(_run_role(args.cmd, args))
    except KeyboardInterrupt:
        pass
    return 0


if __name__ == "__main__":
    sys.exit(main())
