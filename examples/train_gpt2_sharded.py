"""End-to-end sharded training example: GPT-2 over a (data, pipe, model)
mesh with the input pipeline, checkpointing, and metrics.

Runs anywhere — on a TPU slice it uses the real chips; on a dev box:

    XLA_FLAGS=--xla_force_host_platform_device_count=8 \
    JAX_PLATFORMS=cpu python examples/train_gpt2_sharded.py

Multi-HOST: start the same script on every host with the coordinator
flags (or call initialize_distributed yourself):

    python examples/train_gpt2_sharded.py \
        --coordinator host0:8476 --num-processes 2 --process-id 0
"""

import argparse

# dev-checkout convenience: running from the repo without pip-installing
# puts examples/ (not the root) on sys.path
import os
import sys

sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from tensorlink_tpu.config import DistributedConfig, MeshConfig, TrainConfig
from tensorlink_tpu.data import ShardedLoader, prefetch_to_device
from tensorlink_tpu.models.gpt2 import GPT2, GPT2Config
from tensorlink_tpu.parallel.engine import ShardedTrainer
from tensorlink_tpu.runtime.mesh import initialize_distributed, make_mesh
from tensorlink_tpu.train.trainer import softmax_cross_entropy


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--coordinator", default=None)
    ap.add_argument("--num-processes", type=int, default=None)
    ap.add_argument("--process-id", type=int, default=None)
    ap.add_argument("--steps", type=int, default=50)
    args = ap.parse_args()

    info = initialize_distributed(DistributedConfig(
        coordinator=args.coordinator,
        num_processes=args.num_processes,
        process_id=args.process_id,
    ))
    if info["enabled"]:
        print(f"process {info['process_id']}/{info['num_processes']}, "
              f"{info['global_devices']} global devices")

    n = len(jax.devices())
    # factor the device count into (data, pipe, model): tweak per topology
    mesh_cfg = MeshConfig(data=max(n // 4, 1), pipe=min(2, n),
                          model=2 if n >= 4 else 1)
    mesh = make_mesh(mesh_cfg)
    print("mesh:", dict(mesh.shape))

    model = GPT2(GPT2Config(
        vocab_size=512, dim=128, num_layers=4, num_heads=4, max_len=128,
        dropout=0.1,
    ))
    params = model.init(jax.random.key(0))
    # bf16 on accelerators; f32 on the CPU dev mesh (XLA's CPU
    # AllReducePromotion pass crashes on bf16 cross-replica reduces)
    dtype = "bfloat16" if jax.default_backend() != "cpu" else "float32"
    trainer = ShardedTrainer(
        mesh,
        TrainConfig(batch_size=32, micro_batches=4, learning_rate=3e-4,
                    optimizer="adamw", pp_schedule="1f1b", dtype=dtype),
        model.as_pipeline_parts(params),
        lambda logits, batch: softmax_cross_entropy(logits, batch["labels"]),
    )
    state = trainer.init_state()
    print("engine:", trainer.describe())

    # toy corpus; swap in your tokenized dataset (np.memmap works too)
    r = np.random.default_rng(0)
    ids = r.integers(0, 512, (2048, 65))
    loader = ShardedLoader(
        {"input_ids": ids[:, :-1], "labels": ids[:, 1:]},
        global_batch=32, seed=0,
    )
    sharding = NamedSharding(mesh, P(("data",)))
    step = 0
    for batch in prefetch_to_device(loader.epochs(100), sharding):
        state, metrics = trainer.train_step(state, batch)
        if step % 10 == 0:
            print(f"step {step}: loss {float(metrics['loss']):.4f} "
                  f"gnorm {float(metrics['grad_norm']):.3f}")
        step += 1
        if step >= args.steps:
            break
    print("done:", float(metrics["loss"]))


if __name__ == "__main__":
    main()
