"""Sharded LLM serving example: tensor-parallel KV-cache generation with
optional int8 weight-only quantization, sliding-window attention
(Mistral), and nucleus sampling.

Runs anywhere — on a TPU slice it uses the real chips; on a dev box:

    XLA_FLAGS=--xla_force_host_platform_device_count=8 \
    JAX_PLATFORMS=cpu python examples/serve_llm.py --tp 8

Real checkpoints: load HF weights with models/hf_import (LlamaForCausalLM
and MistralForCausalLM share the mapping) instead of the random init here:

    from tensorlink_tpu.models.hf_import import (
        llama_params_from_hf, load_safetensors,
    )
    params = llama_params_from_hf(load_safetensors(path), cfg)
"""

import argparse

# dev-checkout convenience: running from the repo without pip-installing
# puts examples/ (not the root) on sys.path
import os
import sys

sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)

import jax
import jax.numpy as jnp
import numpy as np

from tensorlink_tpu.config import MeshConfig
from tensorlink_tpu.models.llama import Llama, LlamaConfig
from tensorlink_tpu.parallel.inference import GenerationConfig, InferenceEngine
from tensorlink_tpu.runtime.mesh import make_mesh


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--tp", type=int, default=1, help="model-axis devices")
    ap.add_argument("--window", type=int, default=None,
                    help="sliding-window attention (Mistral-style)")
    ap.add_argument("--int8", action="store_true",
                    help="weight-only int8 quantized serving")
    ap.add_argument("--max-new", type=int, default=32)
    ap.add_argument("--temperature", type=float, default=0.8)
    ap.add_argument("--top-p", type=float, default=0.95)
    ap.add_argument("--continuous", action="store_true",
                    help="serve staggered requests through the "
                         "continuous-batching scheduler (submit/result) "
                         "instead of one static generate() batch")
    ap.add_argument("--paged", action="store_true",
                    help="continuous batching over the paged KV cache "
                         "(block pool + copy-on-write prefix sharing + "
                         "chunked prefill); demonstrates shared-system-"
                         "prompt traffic hitting the prefix cache")
    ap.add_argument("--slots", type=int, default=4,
                    help="decode slots for --continuous/--paged")
    args = ap.parse_args()

    # tiny config so the example runs on a dev box; swap for
    # LlamaConfig.llama3_8b() / .mistral_7b() + HF weights in production
    cfg = LlamaConfig(
        vocab_size=512, dim=64, num_layers=2, num_heads=8, num_kv_heads=4,
        hidden_dim=128, max_len=256, rope_theta=10000.0,
        attn_window=args.window,
    )
    model = Llama(cfg)
    params = model.init(jax.random.key(0))

    mesh = make_mesh(MeshConfig(model=args.tp))
    eng = InferenceEngine(
        mesh, model, params, max_len=256,
        quantize="int8" if args.int8 else None,
        # windowed models serve from a ring KV cache: O(prompt+window)
        # memory no matter how long the generation runs — the static
        # path only; the continuous scheduler uses the monotone cache
        rolling_cache=(
            args.window is not None
            and not args.continuous and not args.paged
        ),
    )
    gen = GenerationConfig(
        max_new_tokens=args.max_new,
        temperature=args.temperature,
        top_p=args.top_p,
    )
    rng = np.random.default_rng(0)
    print(f"mesh={dict(mesh.shape)} window={cfg.attn_window} "
          f"int8={args.int8} continuous={args.continuous} "
          f"paged={args.paged}")
    if args.paged:
        # shared-prefix traffic: every request opens with the same
        # "system prompt". The first prefill writes those tokens into
        # pool blocks and registers them in the prefix index; every
        # later request maps the resident blocks (refcount++) and
        # prefills ONLY its unique suffix. A request that would extend
        # a block other requests still share gets a copy-on-write
        # duplicate instead. HBM holds live blocks, not slots*max_len.
        from tensorlink_tpu.parallel.serving import (
            PagedContinuousBatchingEngine,
        )

        sch = PagedContinuousBatchingEngine(
            eng, slots=args.slots, gen=gen, decode_chunk=8,
            block_size=16, prefill_chunk=16,
        )
        system = rng.integers(0, cfg.vocab_size, (24,))
        rids = [
            sch.submit(
                np.concatenate(
                    [system, rng.integers(0, cfg.vocab_size, (n,))]
                ),
                seed=i,
            )
            for i, n in enumerate((5, 8, 3, 11, 6, 8))
        ]
        for rid in rids:
            print(f"request {rid}:", sch.result(rid))
        st = sch.stats()
        print(
            f"prefix hit rate {st['prefix_cache_hit_rate']:.2f} "
            f"({st['prefix_matched_tokens']}/{st['prompt_tokens_total']} "
            f"prompt tokens served from resident blocks); "
            f"peak blocks {st['peak_blocks_in_use']} "
            f"of {st['pool']['num_blocks']}"
        )
    elif args.continuous:
        # staggered traffic: variable-length prompts submitted one by
        # one, interleaved prefill+decode over a fixed slot batch;
        # per-request seeds keep each stream deterministic under any
        # co-tenant traffic
        from tensorlink_tpu.parallel.serving import ContinuousBatchingEngine

        sch = ContinuousBatchingEngine(
            eng, slots=args.slots, gen=gen, decode_chunk=8,
            prefill_block=8,
        )
        rids = [
            sch.submit(rng.integers(0, cfg.vocab_size, (n,)), seed=i)
            for i, n in enumerate((5, 8, 3, 11, 6, 8))
        ]
        for rid in rids:
            print(f"request {rid}:", sch.result(rid))
        print("scheduler:", sch.stats())
    else:
        prompts = jnp.asarray(rng.integers(0, cfg.vocab_size, (2, 8)))
        tokens = eng.generate(prompts, gen, rng=jax.random.key(0))
        print("generated:", np.asarray(tokens))


if __name__ == "__main__":
    main()
