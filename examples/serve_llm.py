"""Sharded LLM serving example: tensor-parallel KV-cache generation with
optional int8 weight-only quantization, sliding-window attention
(Mistral), and nucleus sampling.

Runs anywhere — on a TPU slice it uses the real chips; on a dev box:

    XLA_FLAGS=--xla_force_host_platform_device_count=8 \
    JAX_PLATFORMS=cpu python examples/serve_llm.py --tp 8

Real checkpoints: load HF weights with models/hf_import (LlamaForCausalLM
and MistralForCausalLM share the mapping) instead of the random init here:

    from tensorlink_tpu.models.hf_import import (
        llama_params_from_hf, load_safetensors,
    )
    params = llama_params_from_hf(load_safetensors(path), cfg)
"""

import argparse

# dev-checkout convenience: running from the repo without pip-installing
# puts examples/ (not the root) on sys.path
import os
import sys

sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)

import jax
import jax.numpy as jnp
import numpy as np

from tensorlink_tpu.config import MeshConfig
from tensorlink_tpu.models.llama import Llama, LlamaConfig
from tensorlink_tpu.parallel.inference import GenerationConfig, InferenceEngine
from tensorlink_tpu.runtime.mesh import make_mesh


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--tp", type=int, default=1, help="model-axis devices")
    ap.add_argument("--window", type=int, default=None,
                    help="sliding-window attention (Mistral-style)")
    ap.add_argument("--int8", action="store_true",
                    help="weight-only int8 quantized serving")
    ap.add_argument("--max-new", type=int, default=32)
    ap.add_argument("--temperature", type=float, default=0.8)
    ap.add_argument("--top-p", type=float, default=0.95)
    ap.add_argument("--continuous", action="store_true",
                    help="serve staggered requests through the "
                         "continuous-batching scheduler (submit/result) "
                         "instead of one static generate() batch")
    ap.add_argument("--paged", action="store_true",
                    help="continuous batching over the paged KV cache "
                         "(block pool + copy-on-write prefix sharing + "
                         "chunked prefill); demonstrates shared-system-"
                         "prompt traffic hitting the prefix cache")
    ap.add_argument("--kv-quant", default=None, choices=("int8",),
                    help="store the paged KV pools as int8 with "
                         "per-slot scales (quantized at write, "
                         "dequantized in-kernel at read): ~2x less KV "
                         "HBM and disagg wire bytes; implies --paged")
    ap.add_argument("--slots", type=int, default=4,
                    help="decode slots for --continuous/--paged")
    ap.add_argument("--priority", default="standard",
                    choices=("interactive", "standard", "batch"),
                    help="SLO class for the submitted requests: under "
                         "pressure the scheduler preempts/sheds BATCH "
                         "before STANDARD before INTERACTIVE")
    ap.add_argument("--deadline", type=float, default=None,
                    help="per-request deadline in seconds: admission "
                         "rejects provably-unmeetable deadlines from "
                         "the measured TPOT, and a request whose "
                         "deadline passes is cancelled (slot + KV "
                         "blocks freed) with a typed error")
    ap.add_argument("--max-queue", type=int, default=None,
                    help="bound pending admissions: overflow is SHED "
                         "with a typed OverloadedError carrying a "
                         "measured retry_after_s (try --slots 1 "
                         "--max-queue 1 to watch a shed + honored "
                         "retry-after live)")
    ap.add_argument("--speculate", action="store_true",
                    help="speculative decoding with a DRAFT MODEL (the "
                         "target's int8 sibling here): the draft "
                         "proposes K tokens, the target verifies all "
                         "K+1 positions in one weight pass; greedy "
                         "output is token-identical, acceptance stats "
                         "are printed")
    ap.add_argument("--ngram", action="store_true",
                    help="speculative decoding WITHOUT a draft model: "
                         "n-gram/prompt-lookup proposals from the "
                         "request's own context (the no-tiny-sibling "
                         "fallback), same verify program")
    ap.add_argument("--spec-k", default="4",
                    help="drafted tokens per verify pass, or 'auto': "
                         "per-request K self-tunes online from the "
                         "measured acceptance (masked K inside the one "
                         "spec program — no retrace), with draft "
                         "early-exit and LOW-ACCEPT self-healing")
    ap.add_argument("--draft", default=None,
                    help="'auto': measure the model zoo's candidate "
                         "drafts at engine start and keep the largest "
                         "one whose accepted-tokens-per-second beats "
                         "this engine's own non-spec baseline "
                         "(falls back to n-gram, then non-spec)")
    ap.add_argument("--autotune-dir", default=None,
                    help="persistent tuning store (runtime/autotune.py)"
                         ": flash blocks, prefill buckets, and the "
                         "learned K prior reload here across restarts")
    ap.add_argument("--slo", default=None, metavar="SLO_JSON",
                    help="SLO rule file (see examples/slo.json): the "
                         "run samples its own metrics into ring-buffer "
                         "time-series and prints the LIVE burn-rate "
                         "per rule (observed vs target) plus any "
                         "firing alerts after every finished request — "
                         "the same rules `tldiag check`/a node's "
                         "alert engine evaluate")
    ap.add_argument("--ledger", action="store_true",
                    help="meter every request, sign a WorkReceipt per "
                         "finished request with a dev identity, audit "
                         "the receipts locally, and print the tenant/"
                         "worker ledger (runtime/ledger.py)")
    ap.add_argument("--profile-dir", default=None,
                    help="capture the whole serving run under "
                         "jax.profiler into this directory (open with "
                         "tensorboard --logdir DIR / xprof); the "
                         "always-on device-time attribution prints "
                         "either way")
    ap.add_argument("--disaggregate", action="store_true",
                    help="DISTRIBUTED serving demo over two local p2p "
                         "nodes: a validator places each request's "
                         "prefill leg on the highest-TFLOPs worker and "
                         "its decode leg on the highest-HBM worker, "
                         "the filled KV blocks cross the wire "
                         "(CRC-framed, byte-counted), and the output "
                         "is token-identical to colocated serving")
    ap.add_argument("--pipeline", type=int, default=0, metavar="N",
                    help="PIPELINE-SHARDED serving demo over N local "
                         "p2p stage workers: the layer stack is "
                         "partitioned proportional to each worker's "
                         "advertised HBM, every worker holds ONLY its "
                         "span's weights + KV, activations stream "
                         "stage-to-stage over the ACT_FWD wire each "
                         "decode tick, and the output is "
                         "token-identical to a single node holding "
                         "the whole model")
    args = ap.parse_args()
    if args.disaggregate and args.pipeline:
        ap.error("--disaggregate and --pipeline are exclusive")
    if args.pipeline:
        _pipeline_demo(args)
        return
    if args.disaggregate:
        _disaggregate_demo(args)
        return
    if args.speculate and args.ngram:
        ap.error("--speculate and --ngram are exclusive")
    if args.draft is not None and args.draft != "auto":
        ap.error("--draft only supports 'auto' (or use --speculate)")
    spec_auto = args.spec_k == "auto"
    spec_k = 4 if spec_auto else int(args.spec_k)
    if (
        args.speculate or args.ngram or args.draft or spec_auto
    ) and not (args.continuous or args.paged):
        args.continuous = True  # speculation lives in the schedulers
    if args.ledger and not (args.continuous or args.paged):
        args.paged = True  # metering lives in the schedulers
    if args.kv_quant and not args.paged:
        if args.continuous:
            ap.error("--kv-quant needs the paged engine (drop "
                     "--continuous or add --paged)")
        args.paged = True  # quantized KV lives in the block pools

    # tiny config so the example runs on a dev box; swap for
    # LlamaConfig.llama3_8b() / .mistral_7b() + HF weights in production
    cfg = LlamaConfig(
        vocab_size=512, dim=64, num_layers=2, num_heads=8, num_kv_heads=4,
        hidden_dim=128, max_len=256, rope_theta=10000.0,
        attn_window=args.window,
    )
    model = Llama(cfg)
    params = model.init(jax.random.key(0))

    mesh = make_mesh(MeshConfig(model=args.tp))
    eng = InferenceEngine(
        mesh, model, params, max_len=256,
        quantize="int8" if args.int8 else None,
        # windowed models serve from a ring KV cache: O(prompt+window)
        # memory no matter how long the generation runs — the static
        # path only; the continuous scheduler uses the monotone cache
        rolling_cache=(
            args.window is not None
            and not args.continuous and not args.paged
        ),
    )
    gen = GenerationConfig(
        max_new_tokens=args.max_new,
        temperature=args.temperature,
        top_p=args.top_p,
    )
    rng = np.random.default_rng(0)
    print(f"mesh={dict(mesh.shape)} window={cfg.attn_window} "
          f"int8={args.int8} continuous={args.continuous} "
          f"paged={args.paged} speculate={args.speculate} "
          f"ngram={args.ngram}")

    # speculative decoding: one verify-K weight pass of the TARGET
    # emits 1..K+1 tokens. --speculate drafts with the target's own
    # int8 sibling (half the weight bytes per draft step; int8 keeps
    # the argmax, so greedy acceptance is high — swap in a genuinely
    # small model when the zoo has one for your target); --ngram drafts
    # from the request's own context, no second model at all.
    spec_kw = {}
    if args.speculate or args.ngram or args.draft or spec_auto:
        from tensorlink_tpu.parallel.serving import SpecConfig

        scfg = (
            SpecConfig.auto(k=spec_k) if spec_auto
            else SpecConfig(k=spec_k)
        )
        spec_kw["speculative"] = scfg
        if args.draft == "auto":
            # measured pairing: a short calibration burst per candidate
            # decides whether ANY draft (or n-gram, or nothing) pays on
            # this chip for this model — no tokens-per-weight heuristics
            from tensorlink_tpu.parallel.serving import autopair_draft

            verdict = autopair_draft(eng, gen, cfg=scfg)
            print(
                f"draft auto-pairing: {verdict['name']} "
                f"(mode={verdict['mode']}, measured tok/s "
                f"{verdict['measured']}, baseline "
                f"{verdict['baseline_tokens_per_sec']}, burst "
                f"{verdict['calibration_s']}s)"
            )
            spec_kw["draft"] = verdict["draft"]
            if verdict["mode"] == "nonspec":
                spec_kw.pop("speculative")
        elif args.speculate:
            spec_kw["draft"] = InferenceEngine(
                mesh, model, params, max_len=256, quantize="int8",
            )
    if args.autotune_dir:
        spec_kw["autotune_dir"] = args.autotune_dir
    if args.max_queue is not None:
        spec_kw["max_queue"] = args.max_queue

    slo_mon = None
    if args.slo:
        from tensorlink_tpu.runtime.alerts import (
            AlertEngine,
            evaluate_rule,
            load_rules,
        )
        from tensorlink_tpu.runtime.metrics import Metrics
        from tensorlink_tpu.runtime.timeseries import TimeSeriesStore

        slo_rules = load_rules(args.slo)
        slo_store = TimeSeriesStore()
        slo_engine = AlertEngine(slo_rules)
        spec_kw["metrics"] = slo_metrics = Metrics()

        def slo_mon(sch):
            """One sampler tick + live burn-rate line: what a node's
            _timeseries_loop does every second, printed inline."""
            slo_store.sample_metrics(slo_metrics)
            slo_engine.evaluate(slo_store)
            parts = []
            for r in slo_rules:
                if r.kind not in ("latency", "budget_burn"):
                    continue
                v = evaluate_rule(r, slo_store).value
                if v is None:
                    continue  # no traffic in this class yet
                tgt = (
                    r.target if r.kind == "latency"
                    else r.budget_frac * r.burn_factor
                )
                parts.append(f"{r.name}={v:.4g}/{tgt:g}")
            firing = ",".join(
                a["name"] for a in slo_engine.active()
            ) or "none"
            print(
                f"  slo burn (observed/target): "
                f"{' '.join(parts) or '(no data yet)'}  firing={firing}"
            )

    def submit_all(sch, prompt_list):
        """Submit with the chosen SLO class/deadline; a shed request
        prints its typed 429 and HONORS the advertised retry-after
        (pumping the scheduler while waiting) before retrying."""
        import time as _t

        from tensorlink_tpu.parallel.serving import (
            DeadlineExceededError,
            OverloadedError,
        )

        rids = []
        for i, pr in enumerate(prompt_list):
            while True:
                try:
                    rids.append(sch.submit(
                        pr, seed=i, priority=args.priority,
                        deadline_s=args.deadline,
                    ))
                    break
                except OverloadedError as e:
                    print(
                        f"request {i} SHED ({e.reason}): advertised "
                        f"retry_after_s={e.retry_after_s} — honoring it"
                    )
                    t0 = _t.perf_counter()
                    while _t.perf_counter() - t0 < (e.retry_after_s or 0.05):
                        sch.step()
                except DeadlineExceededError as e:
                    print(f"request {i} rejected at admission: {e}")
                    rids.append(None)
                    break
        return rids

    def print_result(sch, rid):
        from tensorlink_tpu.parallel.serving import (
            DeadlineExceededError,
            OverloadedError,
        )

        if rid is None:
            return
        try:
            print(f"request {rid}:", sch.result(rid))
        except DeadlineExceededError as e:
            print(f"request {rid} MISSED its deadline (cancelled, "
                  f"slot/blocks freed): {e}")
        except OverloadedError as e:
            print(f"request {rid} shed ({e.reason}), retry_after_s="
                  f"{e.retry_after_s}")

    def print_spec(st) -> None:
        sp = st.get("spec")
        if sp:
            print(
                f"speculation[{sp['mode']}]: "
                f"{sp['accepted_tokens_per_weight_pass']} accepted "
                f"tokens/weight-pass (acceptance {sp['acceptance_rate']}, "
                f"{sp['emitted_tokens']} tokens over "
                f"{sp['weight_passes']} passes, "
                f"{sp['fallback_total']} n-gram misses)"
            )
            if sp.get("adaptive"):
                print(
                    f"adaptive K: mean dispatched K {sp['k_mean']} "
                    f"of k_max {sp['k']}; learned prior "
                    f"{sp['k_prior']}"
                )
        healed = st.get("spec_self_healed")
        if healed:
            print(
                f"self-healed: {healed['from']} -> {healed['to']} at "
                f"acceptance {healed['acceptance']}"
            )
        if st.get("autotune_warm_start_s") is not None:
            print(
                f"autotune warm start: {st['autotune_warm_start_s']}s "
                "(flash blocks + K prior loaded, nothing re-measured)"
            )
        dt = st.get("device_time")
        if dt:
            print(
                f"device time: {dt['device_busy_s']:.4f}s busy / "
                f"{dt['host_gap_s']:.4f}s host gap "
                f"(bubble {dt['host_gap_frac']:.1%})"
            )
            for name, p in dt["programs"].items():
                extra = "".join(
                    f" {k}={p[k]}" for k in ("mfu", "mbu") if k in p
                )
                print(
                    f"  {name}: {p['count']} dispatches, "
                    f"{p['busy_s']:.4f}s busy{extra}"
                )
        tdec = st.get("ttft_decomp")
        if tdec:
            print(f"ttft decomposition (EWMA): {tdec}")

    def print_ledger(sch) -> None:
        """What the worker+validator pair does over the wire, inline:
        sign each finished request's meter, audit the receipts, print
        the tenant/worker ledger tables."""
        from tensorlink_tpu.diag import render_ledger
        from tensorlink_tpu.p2p.crypto import Identity
        from tensorlink_tpu.runtime.ledger import (
            ReceiptAuditor,
            build_receipt,
        )

        ident = Identity.generate()
        aud = ReceiptAuditor()
        for m in sch.drain_meters(1024):
            aud.ingest(build_receipt(m, ident))
        print(render_ledger(aud.snapshot()))

    prof_cm = None
    if args.profile_dir:
        from tensorlink_tpu.runtime.profiling import trace

        prof_cm = trace(args.profile_dir)
        prof_cm.__enter__()
    if args.paged:
        # shared-prefix traffic: every request opens with the same
        # "system prompt". The first prefill writes those tokens into
        # pool blocks and registers them in the prefix index; every
        # later request maps the resident blocks (refcount++) and
        # prefills ONLY its unique suffix. A request that would extend
        # a block other requests still share gets a copy-on-write
        # duplicate instead. HBM holds live blocks, not slots*max_len.
        from tensorlink_tpu.parallel.serving import (
            PagedContinuousBatchingEngine,
        )

        sch = PagedContinuousBatchingEngine(
            eng, slots=args.slots, gen=gen, decode_chunk=8,
            block_size=16, prefill_chunk=16, kv_quant=args.kv_quant,
            **spec_kw,
        )
        if args.kv_quant:
            # what one block of KV costs in this form vs float pools
            # (kv_block_bytes sums every pool incl. the scale siblings;
            # the same ratio applies to HBM footprint AND the disagg
            # wire payload, which ships blocks in pool form)
            hd = cfg.dim // cfg.num_heads
            fp = (
                cfg.num_layers * 2 * sch.block_size
                * cfg.num_kv_heads * hd
                * jnp.dtype(eng.cache_dtype).itemsize
            )
            qb = sch.kv_block_bytes
            print(
                f"kv blocks ({args.kv_quant}): {qb} B/block vs {fp} "
                f"B/block float pools -> {fp / qb:.2f}x less KV HBM "
                f"and wire bytes per token (the f32 scale costs 4 B "
                f"per head_dim={hd} int8 B: production head dims "
                f"approach the full 2x vs bf16)"
            )
        system = rng.integers(0, cfg.vocab_size, (24,))
        rids = submit_all(sch, [
            np.concatenate(
                [system, rng.integers(0, cfg.vocab_size, (n,))]
            )
            for n in (5, 8, 3, 11, 6, 8)
        ])
        ktraj = []
        for rid in rids:
            print_result(sch, rid)
            if slo_mon is not None:
                slo_mon(sch)
            sp = sch.stats().get("spec") or {}
            if sp.get("adaptive"):
                ktraj.append(sp["k_prior"]["k"])
        if ktraj:
            print(f"K trajectory (learned prior per finished request): "
                  f"{ktraj}")
        st = sch.stats()
        print(
            f"prefix hit rate {st['prefix_cache_hit_rate']:.2f} "
            f"({st['prefix_matched_tokens']}/{st['prompt_tokens_total']} "
            f"prompt tokens served from resident blocks); "
            f"peak blocks {st['peak_blocks_in_use']} "
            f"of {st['pool']['num_blocks']}"
        )
        print_spec(st)
        if args.ledger:
            print_ledger(sch)
    elif args.continuous:
        # staggered traffic: variable-length prompts submitted one by
        # one, interleaved prefill+decode over a fixed slot batch;
        # per-request seeds keep each stream deterministic under any
        # co-tenant traffic
        from tensorlink_tpu.parallel.serving import ContinuousBatchingEngine

        sch = ContinuousBatchingEngine(
            eng, slots=args.slots, gen=gen, decode_chunk=8,
            prefill_block=8, **spec_kw,
        )
        rids = submit_all(sch, [
            rng.integers(0, cfg.vocab_size, (n,))
            for n in (5, 8, 3, 11, 6, 8)
        ])
        ktraj = []
        for rid in rids:
            print_result(sch, rid)
            if slo_mon is not None:
                slo_mon(sch)
            sp = sch.stats().get("spec") or {}
            if sp.get("adaptive"):
                ktraj.append(sp["k_prior"]["k"])
        if ktraj:
            print(f"K trajectory (learned prior per finished request): "
                  f"{ktraj}")
        print("scheduler:", sch.stats())
        print_spec(sch.stats())
        if args.ledger:
            print_ledger(sch)
    else:
        prompts = jnp.asarray(rng.integers(0, cfg.vocab_size, (2, 8)))
        tokens = eng.generate(prompts, gen, rng=jax.random.key(0))
        print("generated:", np.asarray(tokens))
    if prof_cm is not None:
        prof_cm.__exit__(None, None, None)
        print(
            f"jax.profiler capture in {args.profile_dir} — open with: "
            f"tensorboard --logdir {args.profile_dir}"
        )




def _pipeline_demo(args) -> None:
    """N stage workers on localhost: the model sliced layer-wise by
    HBM capability, activations as the wire unit (ISSUE 18 / ROADMAP
    2). The point the demo pins: NO single worker holds the full
    weights, yet the token stream is bit-identical to one that does."""
    import asyncio

    from tensorlink_tpu.config import NodeConfig
    from tensorlink_tpu.nn.staging import (
        layer_param_bytes,
        param_bytes,
        stage_spans,
    )
    from tensorlink_tpu.parallel.serving import PagedContinuousBatchingEngine
    from tensorlink_tpu.roles.user import UserNode
    from tensorlink_tpu.roles.validator import ValidatorNode
    from tensorlink_tpu.roles.worker import WorkerNode

    cfg = LlamaConfig(
        vocab_size=512, dim=64, num_layers=4, num_heads=8, num_kv_heads=4,
        hidden_dim=128, max_len=256, rope_theta=10000.0,
    )
    n_stages = max(2, min(int(args.pipeline), cfg.num_layers))
    model = Llama(cfg)
    params = model.init(jax.random.key(0))
    gen = GenerationConfig(
        max_new_tokens=args.max_new, temperature=args.temperature,
        top_p=args.top_p,
    )

    def engine():
        # f32 end to end so the parity print compares bit-exact
        # streams — the stage cut must be invisible to the sampler
        return InferenceEngine(
            make_mesh(MeshConfig()), model, params, max_len=256,
            cache_dtype=jnp.float32, param_dtype=jnp.float32,
        )

    rng = np.random.default_rng(0)
    prompts = [rng.integers(0, cfg.vocab_size, (n,)) for n in (9, 17, 5)]
    ref_eng = PagedContinuousBatchingEngine(
        engine(), slots=2, gen=gen, block_size=16,
    )
    refs = [ref_eng.result(ref_eng.submit(p, seed=i))
            for i, p in enumerate(prompts)]

    # a real deployment measures HBM (WorkerNode capability bench); the
    # demo pins an asymmetric fleet so the capacity-proportional layer
    # split has something to be proportional TO
    total = param_bytes(params)
    caps = [
        int(total * (0.75 if i == 0 else 0.45)) for i in range(n_stages)
    ]
    spans = stage_spans(layer_param_bytes(params), caps)
    print(f"model = {total} param bytes over {cfg.num_layers} layers; "
          f"no worker holds it alone:")
    for i, ((lo, hi), c) in enumerate(zip(spans, caps)):
        print(f"  stage {i}: layers [{lo},{hi}) on a {c}-byte-HBM "
              "worker")

    async def demo():
        nc = lambda role: NodeConfig(  # noqa: E731
            role=role, host="127.0.0.1", port=0, capability_bench=False,
        )
        val = ValidatorNode(nc("validator"))
        ws = [WorkerNode(nc("worker")) for _ in range(n_stages)]
        user = UserNode(nc("user"))
        for n in (val, *ws, user):
            await n.start()
        kw = dict(slots=2, gen=gen, block_size=16, prefill_chunk=16,
                  max_len=256)
        winfo = lambda w: {  # noqa: E731
            "node_id": w.node_id, "host": "127.0.0.1", "port": w.port,
        }
        for i in range(1, n_stages):
            ws[i].pipeline_stage(
                engine(), sid="demo", stage=i, n_stages=n_stages,
                lo=spans[i][0], hi=spans[i][1], **kw,
            )
        vpeer0 = await ws[0].connect("127.0.0.1", val.port)
        ws[0].pipeline_stage(
            engine(), sid="demo", stage=0, n_stages=n_stages,
            lo=spans[0][0], hi=spans[0][1],
            route=[winfo(w) for w in ws[1:]], validator=vpeer0, **kw,
        )
        for i, w in enumerate(ws):
            w.capability = dict(w.capability or {}, hbm_bytes=caps[i])
            await val.ping(await val.connect("127.0.0.1", w.port))
        print("fleet (validator's heartbeat-harvested pipeline view):")
        for nid, rec in val.peer_capabilities.items():
            print(f"  {nid[:8]}  stage={rec.get('pipe_stage')}/"
                  f"{rec.get('pipe_n_stages')} "
                  f"layers=[{rec.get('pipe_lo')},{rec.get('pipe_hi')}) "
                  f"hbm_bytes={rec.get('hbm_bytes')} "
                  f"kv_free={rec.get('kv_blocks_free')}")
        client = user.remote_serving(
            await user.connect("127.0.0.1", val.port), pipeline=True,
        )
        for i, (p, ref) in enumerate(zip(prompts, refs)):
            rid = await client.submit(p, seed=i)
            out = await client.result(rid)
            parity = "token-identical" if np.array_equal(out, ref) \
                else "MISMATCH"
            print(f"request {i}: {len(p)}-token prompt -> "
                  f"{out.tolist()} ({parity} vs single-node)")
        coord = ws[0].serving.stats()["pipeline"]
        print(f"head coordinator: ticks={coord['ticks']} "
              f"act_wire_bytes={coord['act_wire_bytes']} "
              f"failovers={coord['failovers']}")
        for i, w in enumerate(ws):
            st = w._pipe_stage.stats()
            c = w.metrics.snapshot()["counters"]
            print(f"stage {i} (layers {st['layers']}): "
                  f"decode_steps={st['decode_steps']} "
                  f"bubble_frac={st['bubble_frac']:.3f} "
                  f"act_wire_bytes_total="
                  f"{c.get('act_wire_bytes_total', 0)}")
        for n in (user, val, *ws):
            await n.stop()

    asyncio.run(demo())


def _disaggregate_demo(args) -> None:
    """Two worker nodes on localhost: prefill on one, decode on the
    other, paged KV blocks as the wire unit (ISSUE 15 / ROADMAP 1)."""
    import asyncio

    from tensorlink_tpu.config import NodeConfig
    from tensorlink_tpu.parallel.serving import PagedContinuousBatchingEngine
    from tensorlink_tpu.roles.user import UserNode
    from tensorlink_tpu.roles.validator import ValidatorNode
    from tensorlink_tpu.roles.worker import WorkerNode

    cfg = LlamaConfig(
        vocab_size=512, dim=64, num_layers=2, num_heads=8, num_kv_heads=4,
        hidden_dim=128, max_len=256, rope_theta=10000.0,
    )
    model = Llama(cfg)
    params = model.init(jax.random.key(0))
    gen = GenerationConfig(
        max_new_tokens=args.max_new, temperature=args.temperature,
        top_p=args.top_p,
    )

    def engine():
        return InferenceEngine(make_mesh(MeshConfig()), model, params,
                               max_len=256)

    rng = np.random.default_rng(0)
    system = rng.integers(0, cfg.vocab_size, (24,))
    prompts = [
        np.concatenate([system, rng.integers(0, cfg.vocab_size, (n,))])
        for n in (9, 17, 5)
    ]
    # colocated reference for the token-parity check
    ref_eng = PagedContinuousBatchingEngine(
        engine(), slots=2, gen=gen, block_size=16,
    )
    refs = [ref_eng.result(ref_eng.submit(p, seed=i))
            for i, p in enumerate(prompts)]

    async def demo():
        nc = lambda role: NodeConfig(  # noqa: E731
            role=role, host="127.0.0.1", port=0, capability_bench=False,
        )
        val, wp, wd = ValidatorNode(nc("validator")), WorkerNode(
            nc("worker")), WorkerNode(nc("worker"))
        user = UserNode(nc("user"))
        for n in (val, wp, wd, user):
            await n.start()
        kw = dict(slots=2, gen=gen, block_size=16)
        wp.serving_engine(engine(), paged=True, mode="prefill", **kw)
        wd.serving_engine(engine(), paged=True, mode="decode", **kw)
        # a real deployment measures these (WorkerNode capability
        # microbench); the demo pins an asymmetric fleet so the
        # roofline placement has something to choose between
        wp.capability = {"peak_tflops": 400.0, "hbm_gbps": 50.0}
        wd.capability = {"peak_tflops": 40.0, "hbm_gbps": 800.0}
        for w in (wp, wd):
            await val.ping(await val.connect("127.0.0.1", w.port))
        print("fleet (validator's heartbeat-harvested roofline view):")
        for nid, rec in val.peer_capabilities.items():
            print(f"  {nid[:8]}  mode={rec['serving_mode']:9s} "
                  f"peak_tflops={rec.get('peak_tflops')} "
                  f"hbm_gbps={rec.get('hbm_gbps')} "
                  f"kv_free={rec.get('kv_blocks_free')}")
        client = user.remote_serving(
            await user.connect("127.0.0.1", val.port)
        )
        for i, (p, ref) in enumerate(zip(prompts, refs)):
            rid = await client.submit(p, seed=i)
            out = await client.result(rid)
            parity = "token-identical" if np.array_equal(out, ref) \
                else "MISMATCH"
            print(f"request {i}: {len(p)}-token prompt -> "
                  f"{out.tolist()} ({parity} vs colocated)")
        for name, w in (("prefill", wp), ("decode", wd)):
            c = w.metrics.snapshot()["counters"]
            st = w.serving.stats().get("disagg", {})
            print(f"{name} worker: kv_wire_bytes_total="
                  f"{c.get('kv_wire_bytes_total')} "
                  f"transfers={c.get('kv_wire_transfers_total')} "
                  f"disagg={st}")
        tid = next(s.trace_id for s in user.tracer.spans()
                   if s.name == "serving.disagg_request")
        spans = [
            (w, s) for w in (user, val, wp, wd)
            for s in w.tracer.spans() if s.trace_id == tid
        ]
        print(f"one stitched trace ({tid[:8]}…) across "
              f"{len({id(w) for w, _ in spans})} nodes:")
        for w, s in spans:
            print(f"  [{w.role:9s}] {s.name} "
                  f"({(s.end_ns - s.start_ns) / 1e6:.1f} ms)")
        for n in (user, val, wp, wd):
            await n.stop()

    asyncio.run(demo())


if __name__ == "__main__":
    main()
