"""MoE capacity-factor sweep: router drop fraction vs training quality.

VERDICT r4 weak #4: the bench shipped a 14.5% token-drop fraction as a
telemetry field with no evidence of what dropping does to loss. This
experiment trains the SAME tiny MoE LM (same init, same data order) at
capacity_factor 1.0 / 1.25 / 2.0 and a dropless control (capacity >=
top_k * tokens, so nothing can overflow), and records final train loss,
eval loss, and the measured drop fraction. Quality impact is a property
of the routing algebra, not the accelerator, so the sweep runs anywhere
(the committed table in BASELINE.md came from the 8-device CPU mesh
host). Run: python examples/moe_capacity_sweep.py [steps]
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np


def run(steps: int = 200) -> dict:
    import dataclasses

    from tensorlink_tpu.models.llama import Llama, LlamaConfig
    from tensorlink_tpu.train.optim import apply_updates, make_optimizer
    from tensorlink_tpu.train.trainer import TrainState, softmax_cross_entropy

    base = LlamaConfig(
        vocab_size=512, dim=64, num_layers=2, num_heads=4, num_kv_heads=4,
        hidden_dim=128, max_len=128, moe_experts=8, moe_top_k=2,
    )
    B, T = 16, 64
    r = np.random.default_rng(0)
    # structured synthetic LM data (repeated motifs) so loss can actually
    # fall below the uniform floor and capacity pressure matters
    motifs = r.integers(0, base.vocab_size, (8, 16))

    def batch_at(step, rng):
        rows = []
        for _ in range(B):
            seq = np.concatenate(
                [motifs[rng.integers(0, len(motifs))] for _ in range(T // 16 + 1)]
            )[: T + 1]
            rows.append(seq)
        a = np.stack(rows)
        return {
            "input_ids": jnp.asarray(a[:, :-1]),
            "labels": jnp.asarray(a[:, 1:]),
        }

    results = {}
    # dropless control: capacity_factor big enough that C >= top_k * T
    for label, cf in (("1.0", 1.0), ("1.25", 1.25), ("2.0", 2.0),
                      ("dropless", float(base.moe_experts * base.moe_top_k))):
        cfg = dataclasses.replace(base, moe_capacity_factor=cf)
        model = Llama(cfg)
        params = model.init(jax.random.key(0))
        opt = make_optimizer("adam", 1e-3)
        state = TrainState.create(params, opt)

        def loss_fn(p, b):
            logits, aux = model.apply_with_aux(p, b["input_ids"])
            return softmax_cross_entropy(logits, b["labels"]) + 0.01 * aux

        @jax.jit
        def step_fn(st, b):
            loss, grads = jax.value_and_grad(loss_fn)(st.params, b)
            upd, os_ = opt.update(grads, st.opt_state, st.params, st.step)
            return TrainState(
                params=apply_updates(st.params, upd), opt_state=os_,
                step=st.step + 1,
            ), loss

        rng = np.random.default_rng(1)  # same data order for every cf
        losses = []
        for i in range(steps):
            state, loss = step_fn(state, batch_at(i, rng))
            losses.append(float(loss))
        eval_b = batch_at(0, np.random.default_rng(2))
        eval_loss = float(loss_fn(state.params, eval_b))
        # drop fraction on what layer-0's router sees after training —
        # via the block's own wiring (TransformerBlock.routing_stats)
        blk = model.children["blocks"].children["0"]
        emb = model.children["tok_emb"].apply(
            state.params["tok_emb"], eval_b["input_ids"]
        )
        stats = blk.routing_stats(state.params["blocks"]["0"], emb)
        results[label] = {
            "capacity_factor": cf,
            "final_train_loss": round(float(np.mean(losses[-10:])), 4),
            "eval_loss": round(eval_loss, 4),
            "drop_fraction": round(float(stats["drop_fraction"]), 4),
        }
        print(label, results[label], flush=True)
    return results


if __name__ == "__main__":
    n = int(sys.argv[1]) if len(sys.argv) > 1 else 200
    out = run(n)
    import json

    print(json.dumps(out))
