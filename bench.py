"""Headline benchmark: BERT-base fine-tune throughput (samples/sec/chip).

The reference's implied e2e workload is a BERT-base sequence-classification
fine-tune (tests/ml/test_full_train.py:56-179 — batch 1, seq 100, Adam) for
which it publishes no numbers (BASELINE.md). We run the same workload shape
TPU-natively: bf16 compute, jit train step, K steps chained inside one
device program (lax.scan) so host/tunnel dispatch overhead is amortized.

FLOPs are counted BOTH ways and cross-checked (round-2 reported 4.1% MFU
while its own throughput implied ~51% — the scanned program's
cost_analysis does not scale the scan body by trip count, VERDICT weak #1):

- xla: cost_analysis of the UNSCANNED single-step program x steps;
- analytic: 6*P*tokens dense + 12*L*B*S^2*D attention matmuls.

The two must agree within 2x or the bench aborts with an error field.
MFU is reported from the XLA count (exact for the program as run).

A secondary long-sequence measurement (seq 512, where attention carries
real weight and the Pallas flash kernel engages) is reported in extra
fields; the primary metric keeps the batch-32/seq-128 shape so
vs_baseline stays comparable with the round-1 recording.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline", ...}.
"""

from __future__ import annotations

import json
import os
import re
import sys
import time
from functools import partial
from pathlib import Path

import jax
import jax.numpy as jnp
import numpy as np

from tensorlink_tpu.models.bert import BertClassifier, BertConfig
from tensorlink_tpu.train.optim import apply_updates, make_optimizer
from tensorlink_tpu.train.trainer import TrainState, softmax_cross_entropy

BATCH = int(os.environ.get("BENCH_BATCH", 32))
SEQ = int(os.environ.get("BENCH_SEQ", 128))
CLASSES = 3
# 50 steps per device call: the tunneled dispatch costs ~10-20 ms per
# call, which at 10 steps/call was ~25% of the measurement (r3: 1016
# samples/s at 10 steps vs 1420 at 50 — same program, same chip)
STEPS_PER_CALL = int(os.environ.get("BENCH_STEPS_PER_CALL", 50))
MEASURE_CALLS = int(os.environ.get("BENCH_MEASURE_CALLS", 3))
_BERT = os.environ.get("BENCH_BERT", "base")  # "base" | "tiny" (smoke only)
# secondary long-seq measurement (batch 8, seq 512); disable with =0
_LONG = os.environ.get("BENCH_LONG", "1") == "1"

# Peak bf16 matmul TFLOP/s and HBM GB/s per chip by device kind (public
# spec sheets); substring-matched against jax device_kind. Used to
# report MFU and the roofline floors.
PEAK_BF16_TFLOPS = (
    ("v5p", 459.0),
    ("v5e", 197.0),
    ("v5 lite", 197.0),
    ("v6e", 918.0),
    ("v6 lite", 918.0),
    ("v4", 275.0),
    ("v3", 123.0),
    ("v2", 45.0),
)
HBM_GBPS = (
    ("v5p", 2765.0),
    ("v5e", 819.0),
    ("v5 lite", 819.0),
    ("v6e", 1638.0),
    ("v6 lite", 1638.0),
    ("v4", 1228.0),
    ("v3", 900.0),
    ("v2", 700.0),
)


def _lookup(table, device_kind: str) -> float | None:
    dk = device_kind.lower()
    for key, val in table:
        if key in dk:
            return val
    return None


def peak_tflops_for(device_kind: str) -> float | None:
    return _lookup(PEAK_BF16_TFLOPS, device_kind)


def hbm_gbps_for(device_kind: str) -> float | None:
    return _lookup(HBM_GBPS, device_kind)


def _backend_probe(timeout_s: float = 120.0) -> tuple[bool, str]:
    """Touch the backend in a SUBPROCESS with a timeout: a degraded
    tunnel can make jax.devices() (or the first device op) block forever
    in a C call that no in-process retry can interrupt — observed r3, a
    ~40 min tunnel outage hung the bench with 0 CPU. The probe is
    disposable; only a responsive backend lets the real run proceed."""
    import subprocess

    try:
        r = subprocess.run(
            [sys.executable, "-c",
             "import jax, jax.numpy as jnp;"
             "jax.devices();"
             "print(float(jnp.sum(jnp.ones((8, 8)))))"],
            timeout=timeout_s, capture_output=True,
        )
        if r.returncode == 0:
            return True, ""
        # surface the child's actual error — 'tunnel down' must not mask
        # a broken install / held device / OOM
        return False, (r.stderr or b"").decode(errors="replace")[-300:]
    except subprocess.TimeoutExpired:
        return False, f"probe timed out after {timeout_s:.0f}s"


def backend_with_retry(budget_s: float | None = None):
    """Initialize the accelerator backend, retrying transient tunnel
    failures ('Unable to initialize backend') AND hangs (subprocess
    probe); returns jax.devices().

    Retries span the driver's whole time budget (default 45 min,
    BENCH_PROBE_BUDGET_S to override) with capped backoff — the round-3
    bench gave up after ~10 min into a ~40 min tunnel outage and the
    round's perf record was rc=1 (VERDICT r3 weak #1). Heartbeats go to
    stderr so the single stdout JSON line stays clean.
    """
    if budget_s is None:
        budget_s = float(os.environ.get("BENCH_PROBE_BUDGET_S", 2700))
    if budget_s <= 0:
        # explicit bypass: the caller already initialized/forced a
        # backend in-process (CPU smoke tests, pre-warmed runners) — the
        # subprocess probe would dial the DEFAULT platform instead
        return jax.devices()
    t0 = time.monotonic()
    last, attempt, delay = None, 0, 10.0
    while True:
        attempt += 1
        ok, why = _backend_probe()
        if ok:
            try:
                return jax.devices()
            except RuntimeError as e:  # jax raises RuntimeError on init
                last = e
                if "nable to initialize backend" not in str(e):
                    raise
                try:
                    import jax.extend.backend as _jeb

                    _jeb.clear_backends()
                except Exception:
                    pass
        else:
            last = RuntimeError(f"backend probe failed: {why}")
        elapsed = time.monotonic() - t0
        print(
            f"[bench] backend attempt {attempt} failed at t={elapsed:.0f}s "
            f"(budget {budget_s:.0f}s): {last}",
            file=sys.stderr, flush=True,
        )
        if elapsed + delay >= budget_s:
            break
        time.sleep(delay)
        delay = min(delay * 2, 300.0)  # capped backoff: 10,20,...,300s
    print(
        json.dumps(
            {
                "metric": f"samples/sec/chip (BERT-{_BERT} fine-tune, batch {BATCH}, seq {SEQ}, bf16)",
                "value": 0.0,
                "unit": "samples/sec/chip",
                "vs_baseline": 0.0,
                "error": (
                    f"backend init failed after {attempt} attempts over "
                    f"{time.monotonic() - t0:.0f}s: {last}"
                ),
            }
        )
    )
    sys.exit(1)


def build(batch_size: int, seq: int, moment_dtype: str = "float32"):
    cfg = BertConfig.tiny() if _BERT == "tiny" else BertConfig.base()
    model = BertClassifier(cfg, num_classes=CLASSES)
    params = model.init(jax.random.key(0))
    opt = make_optimizer("adam", 2e-5, moment_dtype=moment_dtype)
    state = TrainState.create(params, opt)

    r = np.random.default_rng(0)
    batch = {
        "input_ids": jnp.asarray(r.integers(0, cfg.vocab_size, (batch_size, seq))),
        "attention_mask": jnp.ones((batch_size, seq), jnp.int32),
        "labels": jnp.asarray(r.integers(0, CLASSES, (batch_size,))),
    }

    def cast(p):
        return jax.tree.map(
            lambda x: x.astype(jnp.bfloat16)
            if jnp.issubdtype(x.dtype, jnp.floating)
            else x,
            p,
        )

    def loss_fn(params, batch):
        logits = model.apply(
            cast(params), batch["input_ids"], attention_mask=batch["attention_mask"]
        )
        return softmax_cross_entropy(logits, batch["labels"])

    def one_step(state: TrainState, batch):
        loss, grads = jax.value_and_grad(loss_fn)(state.params, batch)
        updates, opt_state = opt.update(grads, state.opt_state, state.params, state.step)
        return (
            TrainState(
                params=apply_updates(state.params, updates),
                opt_state=opt_state,
                step=state.step + 1,
            ),
            loss,
        )

    # donating the carried state avoids a full param+moments copy per call
    @partial(jax.jit, donate_argnums=(0,))
    def multi_step(state, batch):
        def body(s, _):
            s, loss = one_step(s, batch)
            return s, loss

        state, losses = jax.lax.scan(body, state, None, length=STEPS_PER_CALL)
        return state, losses

    return cfg, state, batch, one_step, multi_step


def _bubble_child() -> None:
    """Measured pipeline bubble in a LOCAL-CPU subprocess (invoked as
    ``python bench.py --bubble-child``); prints one JSON dict.

    Why not on the real chip: the driver exposes exactly ONE TPU chip, and
    a >1-stage pipeline needs one device per stage — S>=2 cannot exist on
    the bench hardware. The round-3 dryrun's virtual-CPU measurement was
    dispatch noise (tiny ticks, MULTICHIP_r03 measured 0.78 vs closed-form
    0.20); here the per-tick compute is sized so tick time dominates
    dispatch by >=20x on local CPU (no tunnel: dispatch is sub-ms), which
    is the regime VERDICT r3 weak #3 asked for. tick/dispatch evidence is
    reported alongside the number so validity is checkable.
    """
    from __graft_entry__ import _force_virtual_cpu

    S, M = 4, 8
    _force_virtual_cpu(S)

    import jax as _jax
    import jax.numpy as _jnp

    from tensorlink_tpu.config import MeshConfig, TrainConfig
    from tensorlink_tpu.models.gpt2 import GPT2, GPT2Config
    from tensorlink_tpu.parallel.engine import ShardedTrainer
    from tensorlink_tpu.runtime.mesh import make_mesh
    from tensorlink_tpu.train.trainer import softmax_cross_entropy

    mesh = make_mesh(MeshConfig(pipe=S))
    # sized so a tick is tens of ms (>> sub-ms local dispatch) while the
    # whole multi-point fit stays under ~2 min even on a 1-core host where
    # the S virtual devices serialize
    gcfg = GPT2Config(
        vocab_size=512, dim=256, num_layers=S, num_heads=8, max_len=128,
        dropout=0.0,
    )
    model = GPT2(gcfg)
    params = model.init(_jax.random.key(0))
    parts = model.as_pipeline_parts(params)
    cfg = TrainConfig(
        batch_size=4 * M, micro_batches=M, learning_rate=1e-3,
        optimizer="sgd", dtype="float32",
    )
    tr = ShardedTrainer(
        mesh, cfg, parts, lambda lg, b: softmax_cross_entropy(lg, b["labels"])
    )
    state = tr.init_state()
    r = np.random.default_rng(0)
    ids = r.integers(0, 512, (4 * M, 129))
    batch = {
        "input_ids": _jnp.asarray(ids[:, :-1]),
        "labels": _jnp.asarray(ids[:, 1:]),
    }
    bub = tr.measure_bubble(state, batch, repeats=3)

    # dispatch floor: average time of a trivial jitted call — the fixed
    # per-call overhead the intercept would absorb
    noop = _jax.jit(lambda x: x + 1)
    x = _jnp.zeros((8,))
    float(noop(x)[0])
    t0 = time.perf_counter()
    for _ in range(20):
        x = noop(x)
    float(x[0])
    dispatch_s = (time.perf_counter() - t0) / 20
    bub["dispatch_call_s"] = dispatch_s
    bub["tick_over_dispatch"] = (
        bub["tick_s"] / dispatch_s if dispatch_s > 0 else None
    )
    # serialization validity (cores < stages => bubble unobservable) is
    # decided INSIDE ShardedTrainer.measure_bubble, so the dryrun and
    # this child cannot diverge; host_cores is recorded here for the
    # artifact reader
    try:
        cores = len(os.sched_getaffinity(0))  # cgroup/affinity-aware
    except AttributeError:  # non-Linux
        cores = os.cpu_count() or 1
    bub["host_cores"] = cores
    print(json.dumps({k: (v if not isinstance(v, float) or np.isfinite(v)
                          else None) for k, v in bub.items()}))


def measured_bubble_subprocess(timeout_s: float = 600.0) -> dict:
    """Run _bubble_child in a fresh process (it must re-point jax at a
    4-device virtual CPU platform, which cannot happen in a process whose
    TPU backend is already latched). Returns the child's measurement
    dict, or {"error": ...} on any failure — consumers must check for
    the error key before reading measurement fields."""
    import subprocess

    try:
        r = subprocess.run(
            [sys.executable, os.path.abspath(__file__), "--bubble-child"],
            timeout=timeout_s, capture_output=True,
            cwd=os.path.dirname(os.path.abspath(__file__)),
        )
        if r.returncode != 0:
            return {"error": (r.stderr or b"").decode(errors="replace")[-300:]}
        return json.loads(r.stdout.decode().strip().splitlines()[-1])
    except Exception as e:  # noqa: BLE001 — bubble must not sink the bench
        return {"error": str(e)[:300]}


def read_recorded_baseline() -> float | None:
    """First recorded samples/sec/chip in BASELINE.md, if any."""
    p = Path(__file__).parent / "BASELINE.md"
    if not p.exists():
        return None
    m = re.search(r"recorded_samples_per_sec_per_chip:\s*([0-9.]+)", p.read_text())
    return float(m.group(1)) if m else None


def analytic_step_flops(params, cfg, batch: int, seq: int) -> float:
    """6*P*tokens (2PT fwd + 4PT bwd, the standard dense-transformer
    estimate — a lower bound that omits non-matmul work) + the attention
    score/value matmuls 12*L*B*S^2*D the 6PT form excludes."""
    n_params = sum(int(np.prod(x.shape)) for x in jax.tree.leaves(params))
    dense = 6.0 * n_params * batch * seq
    attn = 12.0 * cfg.num_layers * batch * seq * seq * cfg.dim
    return dense + attn


def xla_step_cost(one_step, state, batch) -> tuple[float | None, float | None]:
    """(flops, bytes accessed) of the UNSCANNED single-step program (the
    scanned program's 'flops' does not scale the scan body by trip
    count). lower() only needs avals, so donated state buffers are fine.
    'bytes accessed' is XLA's main-memory traffic estimate for ONE step
    — the roofline's memory-floor input."""
    try:
        compiled = jax.jit(one_step).lower(state, batch).compile()
        cost = compiled.cost_analysis()
        if isinstance(cost, (list, tuple)):
            cost = cost[0]
        b = cost.get("bytes accessed")
        return float(cost["flops"]), (float(b) if b else None)
    except Exception:
        return None, None


def measure(state, batch, multi_step) -> tuple[float, tuple]:
    """-> (seconds per call, (final state, compiled)). The trailing
    float() is a device->host read that REALLY synchronizes
    (block_until_ready alone does not drain the async dispatch queue on
    tunneled TPU runtimes)."""
    compiled = multi_step.lower(state, batch).compile()
    state, losses = compiled(state, batch)  # warmup
    float(losses[-1])
    t0 = time.perf_counter()
    for _ in range(MEASURE_CALLS):
        state, losses = compiled(state, batch)
    float(losses[-1])
    return (time.perf_counter() - t0) / MEASURE_CALLS, (state, compiled)



def decode_roofline(params, hbm_gbps: float | None, n_layers: int, B: int,
                    P_: int, N: int, kv_head_dim: int,
                    exclude: str = "wpe") -> tuple:
    """Shared decode-roofline accounting (GPT-2 + Llama-8B legs must not
    drift): weight bytes = every param leaf except gather-only embedding
    tables matching ``exclude``; KV bytes = the engine's tight cache
    horizon read per step. -> (weight_bytes, kv_bytes, bound_tok_s|None).
    ``kv_head_dim`` is num_kv_heads * head_dim."""
    from tensorlink_tpu.nn.attention import DECODE_BLOCK

    wbytes = sum(
        int(np.prod(l.shape)) * l.dtype.itemsize
        for path, l in jax.tree_util.tree_flatten_with_path(params)[0]
        if exclude not in str(path)
    )
    Lc = -(-(P_ + N) // DECODE_BLOCK) * DECODE_BLOCK
    kvbytes = 2 * n_layers * B * Lc * kv_head_dim * 2
    bound = (
        hbm_gbps * 1e9 / (wbytes + kvbytes) * B
        if hbm_gbps else None
    )
    return wbytes, kvbytes, bound


def serving_disagg_round() -> dict:
    """Disaggregated prefill/decode round (ISSUE 15): the shared-prefix
    workload served twice — COLOCATED (one paged engine does both
    legs) and DISAGGREGATED (engine A chunk-prefills and exports KV
    blocks, the blobs cross the kvwire codec, engine B imports and
    decodes). Reported: the tokens/s ratio (higher-better; < 1.0 is
    the wire tax, > 1.0 means prefill no longer steals decode
    dispatches), total/ per-token wire bytes (directionless — payload
    size is workload, not regression), a token-parity pin, and the
    per-leg TTFT decomposition (queue / prefill / transfer / import —
    the first token rides the payload, so the import IS the decode
    leg's TTFT share) the colocated path cannot even measure."""
    from tensorlink_tpu.config import MeshConfig
    from tensorlink_tpu.models.gpt2 import GPT2, GPT2Config
    from tensorlink_tpu.parallel.inference import (
        GenerationConfig,
        InferenceEngine,
    )
    from tensorlink_tpu.parallel.kvwire import (
        pack_kv_payload,
        unpack_kv_payload,
    )
    from tensorlink_tpu.parallel.serving import (
        PagedContinuousBatchingEngine,
    )
    from tensorlink_tpu.runtime.mesh import make_mesh

    P0, Nn, NREQ, SLOTS, SYS = 32, 64, 16, 8, 64
    cfg = GPT2Config(qkv_fused=True)
    model = GPT2(cfg)
    params = model.init(jax.random.key(0))

    def engine():
        return InferenceEngine(
            make_mesh(MeshConfig()), model, params, max_len=256
        )

    def paged(eng):
        return PagedContinuousBatchingEngine(
            eng, slots=SLOTS, gen=gen, decode_chunk=16,
            block_size=16, prefill_chunk=64,
        )

    gen = GenerationConfig(max_new_tokens=Nn)
    r = np.random.default_rng(3)
    sys_prompt = r.integers(0, cfg.vocab_size, (SYS,))
    prompts = [
        np.concatenate([sys_prompt, r.integers(0, cfg.vocab_size, (P0,))])
        for _ in range(NREQ)
    ]

    out: dict = {}
    # -- colocated baseline: submit+decode on one engine
    colo = paged(engine())
    colo.result(colo.submit(prompts[0]))  # warm: compile + prefix seed
    t0 = time.perf_counter()
    rids = [colo.submit(p_) for p_ in prompts]
    colo.run_until_idle()
    colo_refs = [np.asarray(colo.result(rid)) for rid in rids]
    colo_dt = time.perf_counter() - t0
    colo_tok = sum(len(t) for t in colo_refs)
    colo_tps = colo_tok / colo_dt
    out["serving_colocated_tokens_per_sec"] = round(colo_tps, 1)

    # -- disaggregated: A prefills + exports, blobs cross the codec,
    # B imports + decodes; both sides keep their prefix caches warm
    A, B = paged(engine()), paged(engine())
    warm = A.prefill_export(prompts[0])
    B.result(B.import_prefill(unpack_kv_payload(pack_kv_payload(warm))))
    from tensorlink_tpu.parallel.serving import OverloadedError

    wire_bytes = 0
    t_prefill = t_wire = t_import = 0.0
    t0 = time.perf_counter()
    drids = []
    for p_ in prompts:
        tp = time.perf_counter()
        payload = A.prefill_export(p_)
        t_prefill += time.perf_counter() - tp
        tw = time.perf_counter()
        blob = pack_kv_payload(payload)
        got = unpack_kv_payload(blob)
        wire_bytes += len(blob)
        t_wire += time.perf_counter() - tw
        td = time.perf_counter()
        while True:
            try:
                drids.append(B.import_prefill(got))
                break
            except OverloadedError:
                # typed backpressure: the decode leg is slot-full —
                # drive it (what its scheduler thread does in a real
                # deployment) until a stream finishes and retry
                B.step()
        t_import += time.perf_counter() - td
    td = time.perf_counter()
    B.run_until_idle()
    t_drain = time.perf_counter() - td
    disagg_toks = [np.asarray(B.result(rid)) for rid in drids]
    disagg_dt = time.perf_counter() - t0
    disagg_tok = sum(len(t) for t in disagg_toks)
    disagg_tps = disagg_tok / disagg_dt
    parity = all(
        np.array_equal(a, b) for a, b in zip(disagg_toks, colo_refs)
    )
    out["serving_disagg_tokens_per_sec"] = round(disagg_tps, 1)
    out["serving_disagg_vs_colocated"] = round(disagg_tps / colo_tps, 3)
    out["serving_disagg_token_parity"] = float(parity)
    out["kv_wire_bytes_total"] = wire_bytes
    out["kv_wire_bytes_per_token"] = round(wire_bytes / disagg_tok, 1)
    # per-leg TTFT decomposition, mean per request (the sequential
    # export loop makes queue wait ~0 here; the network hop in the
    # role path adds its own wire latency on top of the codec's)
    out["disagg_ttft_queue_s"] = float(
        (A.stats().get("ttft_decomp") or {}).get("queue_s", 0.0)
    )
    out["disagg_ttft_prefill_s"] = round(t_prefill / NREQ, 5)
    out["disagg_ttft_transfer_s"] = round(t_wire / NREQ, 5)
    # the decode leg's TTFT contribution is the import/graft (the first
    # token itself rides the payload — prefill sampled it); the full
    # decode drain is throughput, already priced into tokens/s, and
    # must not masquerade as a latency-to-first-token component
    out["disagg_ttft_import_s"] = round(t_import / NREQ, 5)
    out["disagg_decode_drain_s"] = round(t_drain, 5)
    out["disagg_prefix_hit_rate_prefill_leg"] = round(
        A.prefix_hit_rate(), 4
    )
    out["serving_disagg_config"] = (
        f"GPT-2 paged x2, shared {SYS}-token system prompt + {P0} "
        f"unique, {NREQ} requests, {SLOTS} slots, block 16, {Nn} new "
        "tokens; wire = pack+CRC+unpack loopback"
    )

    # -- int8 KV wire (ISSUE 20): the SAME export/loopback/import flow
    # with kv_quant="int8" engines on both legs — the wire ships int8
    # block stacks + f32 scale siblings natively (KV_WIRE_INT8_SCHEMA),
    # never a dequantized intermediate, so bytes/token should drop
    # toward 2x vs the float pools above (scale overhead = 4 bytes per
    # D-vector; zstd squeezes both sides)
    try:
        def paged_q(eng):
            return PagedContinuousBatchingEngine(
                eng, slots=SLOTS, gen=gen, decode_chunk=16,
                block_size=16, prefill_chunk=64, kv_quant="int8",
            )

        Aq, Bq = paged_q(engine()), paged_q(engine())
        warmq = Aq.prefill_export(prompts[0])
        Bq.result(
            Bq.import_prefill(unpack_kv_payload(pack_kv_payload(warmq)))
        )
        qwire = 0
        qrids = []
        for p_ in prompts:
            blob = pack_kv_payload(Aq.prefill_export(p_))
            qwire += len(blob)
            got = unpack_kv_payload(blob)
            while True:
                try:
                    qrids.append(Bq.import_prefill(got))
                    break
                except OverloadedError:
                    Bq.step()
        Bq.run_until_idle()
        qtok = sum(len(Bq.result(rid)) for rid in qrids)
        out["kv_wire_bytes_per_token_int8"] = round(qwire / qtok, 1)
        out["kv_wire_int8_config"] = (
            "same workload, kv_quant=int8 both legs; blobs carry int8 "
            "blocks + f32 per-(slot,head) scales under "
            "KV_WIRE_INT8_SCHEMA"
        )
    except Exception as e:  # noqa: BLE001 — must not sink the round
        out["kv_wire_int8_error"] = str(e)[:200]
    return out


def serving_pipeline_round() -> dict:
    """Pipeline-sharded serving round (ISSUE 18): the same request mix
    served twice — SINGLE-NODE (one paged engine holds every layer)
    and PIPELINED (a 3-stage localhost mesh; each worker holds only
    its layer span's weights + KV, activations cross the ACT_FWD wire
    every tick). Reported: the tokens/s ratio (higher-better; < 1.0
    is the per-token hop tax, which in-flight microbatching must
    hide), a token-parity pin (position-keyed sampling makes the
    pipeline cut bit-invisible), activation wire bytes/token
    (directionless — a property of dim and stage count), and a
    per-stage TTFT decomposition from a 1-token probe: each stage's
    prefill compute share vs the wire+scheduling residual."""
    import asyncio

    from tensorlink_tpu.config import MeshConfig, NodeConfig
    from tensorlink_tpu.models.llama import Llama, LlamaConfig
    from tensorlink_tpu.parallel.inference import (
        GenerationConfig,
        InferenceEngine,
    )
    from tensorlink_tpu.parallel.serving import (
        PagedContinuousBatchingEngine,
    )
    from tensorlink_tpu.runtime.mesh import make_mesh

    P0, Nn, NREQ, SLOTS, STAGES = 24, 24, 8, 4, 3
    cfg = LlamaConfig(
        vocab_size=256, dim=64, num_layers=3, num_heads=4,
        num_kv_heads=2, hidden_dim=128, max_len=128, rope_theta=10000.0,
    )
    model = Llama(cfg)
    params = model.init(jax.random.key(0))

    def engine():
        # float32 end to end: the parity pin compares bit-exact token
        # streams, so the activation hop must not add a cast the
        # single-node program doesn't have
        return InferenceEngine(
            make_mesh(MeshConfig()), model, params, max_len=128,
            cache_dtype=jnp.float32, param_dtype=jnp.float32,
        )

    gen = GenerationConfig(max_new_tokens=Nn)
    r = np.random.default_rng(5)
    warm_prompt = r.integers(0, cfg.vocab_size, (P0,))
    prompts = [
        r.integers(0, cfg.vocab_size, (P0 + (i % 5),)) for i in range(NREQ)
    ]

    out: dict = {}
    # -- single-node baseline: every layer on one engine
    single = PagedContinuousBatchingEngine(
        engine(), slots=SLOTS, gen=gen, decode_chunk=SLOTS,
        block_size=16, prefill_chunk=16,
    )
    single.result(single.submit(warm_prompt, seed=7))  # warm: compile
    t0 = time.perf_counter()
    rids = [single.submit(p_, seed=7) for p_ in prompts]
    single.run_until_idle()
    refs = [np.asarray(single.result(rid)) for rid in rids]
    single_dt = time.perf_counter() - t0
    single_tok = sum(len(t) for t in refs)
    single_tps = single_tok / single_dt
    out["serving_single_node_tokens_per_sec"] = round(single_tps, 1)

    # -- pipelined: 3 stage workers on localhost sockets, head stage
    # coordinates (continuous batching lives across the whole chain)
    async def pipelined() -> dict:
        from tensorlink_tpu.roles.user import UserNode
        from tensorlink_tpu.roles.validator import ValidatorNode
        from tensorlink_tpu.roles.worker import WorkerNode

        def ncfg(role):
            return NodeConfig(
                role=role, host="127.0.0.1", port=0,
                capability_bench=False,
            )

        def winfo(w):
            return {
                "node_id": w.node_id, "host": "127.0.0.1", "port": w.port,
            }

        val = ValidatorNode(ncfg("validator"))
        ws = [WorkerNode(ncfg("worker")) for _ in range(STAGES)]
        user = UserNode(ncfg("user"))
        nodes = [val, *ws, user]
        for n in nodes:
            await n.start()
        try:
            kw = dict(
                slots=SLOTS, gen=gen, block_size=16, prefill_chunk=16,
                max_len=128,
            )
            spans = [(0, 1), (1, 2), (2, 3)]
            for i in (1, 2):
                ws[i].pipeline_stage(
                    engine(), sid="bench", stage=i, n_stages=STAGES,
                    lo=spans[i][0], hi=spans[i][1], **kw,
                )
            vpeer0 = await ws[0].connect("127.0.0.1", val.port)
            ws[0].pipeline_stage(
                engine(), sid="bench", stage=0, n_stages=STAGES,
                lo=0, hi=1, route=[winfo(ws[1]), winfo(ws[2])],
                validator=vpeer0, **kw,
            )
            for w in ws:
                peer = await val.connect("127.0.0.1", w.port)
                await val.ping(peer)
            vpeer = await user.connect("127.0.0.1", val.port)
            client = user.remote_serving(vpeer, pipeline=True)

            # warm the whole chain (compile every stage program)
            rid = await client.submit(warm_prompt, seed=7)
            await client.result(rid)

            def stage_prefill_s():
                return [
                    float(w._pipe_stage.stats()["prefill_s"]) for w in ws
                ]

            # 1-token probe: TTFT decomposed into per-stage prefill
            # compute vs the wire + scheduling residual
            pre0 = stage_prefill_s()
            tp = time.perf_counter()
            rid = await client.submit(prompts[0], seed=7, max_new=1)
            await client.result(rid)
            ttft = time.perf_counter() - tp
            shares = [
                b - a for a, b in zip(pre0, stage_prefill_s())
            ]
            res: dict = {"pipeline_ttft_total_s": round(ttft, 5)}
            for i, s in enumerate(shares):
                res[f"pipeline_ttft_stage{i}_prefill_s"] = round(s, 5)
            res["pipeline_ttft_wire_host_s"] = round(
                max(ttft - sum(shares), 0.0), 5
            )

            tq = time.perf_counter()
            drids = [
                await client.submit(p_, seed=7) for p_ in prompts
            ]
            outs = [
                np.asarray(await client.result(rid)) for rid in drids
            ]
            pipe_dt = time.perf_counter() - tq
            pipe_tok = sum(len(t) for t in outs)
            res["_tps"] = pipe_tok / pipe_dt
            res["pipeline_token_parity"] = float(all(
                np.array_equal(a, b) for a, b in zip(outs, refs)
            ))
            # every transfer is counted once at BOTH sockets' ends
            # (sender after the reply, receiver on ingest), so the
            # bytes that actually crossed a wire = sum / 2
            wire = sum(
                n.metrics.snapshot()["counters"].get(
                    "act_wire_bytes_total", 0
                )
                for n in (val, *ws, user)
            ) / 2
            res["act_wire_bytes_total"] = int(wire)
            res["act_wire_bytes_per_token"] = round(wire / pipe_tok, 1)
            bubbles = [
                float(w._pipe_stage.stats()["bubble_frac"]) for w in ws
            ]
            res["pipeline_bubble_frac"] = round(max(bubbles), 4)
            return res
        finally:
            for n in nodes:
                await n.stop()

    pres = asyncio.run(pipelined())
    pipe_tps = pres.pop("_tps")
    out["serving_pipeline_tokens_per_sec"] = round(pipe_tps, 1)
    out["pipeline_vs_single_node"] = round(pipe_tps / single_tps, 3)
    out.update(pres)
    out["serving_pipeline_config"] = (
        f"Llama {cfg.num_layers}L dim {cfg.dim} f32, {STAGES} stages x "
        f"1 layer on localhost sockets, {NREQ} requests, {SLOTS} "
        f"slots, block 16, {Nn} new tokens; single-node = same engine "
        "unsharded"
    )
    return out


def serving_under_load_round() -> dict:
    """Overload + churn round (ISSUE 14): Poisson-ish arrivals at ~4x
    the measured per-slot service capacity, mixed SLO classes, one
    chaos-scripted mid-run stall (worker-kill emulation), and a
    shed-retry client that HONORS the advertised retry_after_s — which
    is how the honesty ratio (observed successful-retry wait /
    advertised) is measured rather than asserted."""
    from tensorlink_tpu.config import MeshConfig
    from tensorlink_tpu.models.gpt2 import GPT2, GPT2Config
    from tensorlink_tpu.parallel.inference import (
        GenerationConfig,
        InferenceEngine,
    )
    from tensorlink_tpu.parallel.serving import (
        OverloadedError,
        PagedContinuousBatchingEngine,
        Priority,
    )
    from tensorlink_tpu.runtime import chaos
    from tensorlink_tpu.runtime.mesh import make_mesh
    from tensorlink_tpu.runtime.metrics import Metrics

    P_, N_, SLOTS, NREQ, OVERSUB = 32, 32, 8, 40, 4.0
    KILL_AT, KILL_STALL_S = NREQ // 2, 0.25
    lcfg = GPT2Config(qkv_fused=True)
    lmodel = GPT2(lcfg)
    leng = InferenceEngine(
        make_mesh(MeshConfig()), lmodel, lmodel.init(jax.random.key(0)),
        max_len=256,
    )
    gen = GenerationConfig(max_new_tokens=N_)
    rload = np.random.default_rng(7)
    prompts = rload.integers(0, lcfg.vocab_size, (NREQ, P_))
    # 25% INTERACTIVE / 25% STANDARD / 50% BATCH — interactive tenants
    # are the protected minority riding a batch-heavy mix
    prios = [
        (Priority.INTERACTIVE, Priority.STANDARD, Priority.BATCH,
         Priority.BATCH)[i % 4]
        for i in range(NREQ)
    ]

    def new_sched(metrics):
        return PagedContinuousBatchingEngine(
            leng, slots=SLOTS, gen=gen, decode_chunk=8, block_size=16,
            prefill_chunk=32, max_queue=SLOTS, prefix_cache=False,
            metrics=metrics, warm_buckets=True,
        )

    def pump_all(sch, subs):
        rids = [sch.submit(p_, **kw) for p_, kw in subs]
        sch.run_until_idle()
        ntok = sum(len(sch.result(r_)) for r_ in rids)
        return ntok

    # measured capacity: saturate the slots once, tokens/sec -> the
    # request service rate the arrival process oversubscribes
    warm = new_sched(Metrics())
    t0 = time.perf_counter()
    ntok = pump_all(warm, [(p_, {}) for p_ in prompts[:2 * SLOTS]])
    cap_tps = ntok / (time.perf_counter() - t0)
    cap_rps = cap_tps / N_
    mean_gap_s = 1.0 / (cap_rps * OVERSUB)
    gaps = rload.exponential(mean_gap_s, NREQ)

    # uncontended INTERACTIVE baseline: the same class, one at a time —
    # what its p99 TTFT looks like with the slots to itself
    um = Metrics()
    base = new_sched(um)
    for p_ in prompts[:8]:
        base.result(base.submit(p_, priority=Priority.INTERACTIVE))
    ttft_un = um.histograms.get("serving_ttft_s:interactive")

    def drive(sch, *, chaos_kill: bool, retry: bool, with_slo: bool):
        """Open-loop arrivals (the generator never waits for results);
        shed submits re-arrive after their advertised retry_after_s.
        Returns (elapsed_s, client log)."""
        log = {
            "first_shed_t": {}, "advertised": {}, "admit_t": {},
            "attempts": {}, "shed_attempts": 0, "dropped": [],
            "rids": {},
        }
        due = [(float(g), i) for i, g in enumerate(np.cumsum(gaps))]
        start = time.perf_counter()
        k = 0
        pending: list[tuple[float, int]] = []
        while k < len(due) or pending or sch.step():
            now = time.perf_counter() - start
            ready = [e for e in pending if e[0] <= now]
            if k < len(due) and due[k][0] <= now:
                ready.append(due[k])
                k += 1
            if not ready:
                # nothing arriving: drive the scheduler; when it is
                # fully idle too, wait out the next retry/arrival gap
                if not sch.step():
                    time.sleep(0.001)
                continue
            for when, i in ready:
                if (when, i) in pending:
                    pending.remove((when, i))
                if chaos_kill and i not in log["attempts"]:
                    # UNIQUE arrivals only: a retry re-arrival must not
                    # advance the kill script, or the scripted stall
                    # would drift with wall-clock-dependent shed timing
                    chaos.fire("load.arrival", i=i)
                kw = {}
                if with_slo:
                    kw["priority"] = prios[i]
                    if prios[i] == Priority.INTERACTIVE:
                        kw["deadline_s"] = 60.0
                log["attempts"][i] = log["attempts"].get(i, 0) + 1
                try:
                    log["rids"][i] = sch.submit(prompts[i], **kw)
                    if i in log["first_shed_t"]:
                        log["admit_t"][i] = now
                except OverloadedError as e:
                    log["shed_attempts"] += 1
                    log["first_shed_t"].setdefault(i, now)
                    log["advertised"].setdefault(
                        i, e.retry_after_s or mean_gap_s
                    )
                    if not retry or log["attempts"][i] > 4:
                        log["dropped"].append(i)
                    else:
                        pending.append(
                            (now + (e.retry_after_s or mean_gap_s), i)
                        )
        return time.perf_counter() - start, log

    lm = Metrics()
    sch = new_sched(lm)
    plan = chaos.ChaosPlan(seed=7)
    plan.fault("load.arrival", "kill", at=KILL_AT)
    h = chaos.arm(plan, recorder=None, metrics=lm)
    # the injected churn: a failover-blackout stall while the mesh is
    # oversubscribed (in-process worker-kill emulation — the p2p kill
    # path itself is chaos-tested in tests/test_overload.py)
    h.on_kill("kill", lambda **ctx: time.sleep(KILL_STALL_S))
    try:
        elapsed, log = drive(
            sch, chaos_kill=True, retry=True, with_slo=True
        )
    finally:
        # an armed harness outliving this round would contaminate
        # every later bench measurement with hook-lock overhead
        chaos.disarm()

    o: dict = {}
    ntok = 0
    for i, rid in log["rids"].items():
        try:
            ntok += len(sch.result(rid))
        except Exception:  # noqa: BLE001 — displaced/deadline-missed
            pass
    o["serving_load_tokens_per_sec"] = round(ntok / elapsed, 1)
    o["serving_load_oversubscription"] = OVERSUB
    o["serving_load_worker_kill"] = (
        f"arrival {KILL_AT}: {KILL_STALL_S}s dispatch blackout"
    )
    for cls in ("interactive", "standard", "batch"):
        th = lm.histograms.get(f"serving_ttft_s:{cls}")
        tp = lm.histograms.get(f"serving_tpot_s:{cls}")
        if th is not None:
            o[f"serving_load_{cls}_ttft_p50_s"] = round(th.quantile(0.5), 5)
            o[f"serving_load_{cls}_ttft_p99_s"] = round(th.quantile(0.99), 5)
        if tp is not None:
            o[f"serving_load_{cls}_tpot_p50_s"] = round(tp.quantile(0.5), 6)
            o[f"serving_load_{cls}_tpot_p99_s"] = round(tp.quantile(0.99), 6)
    shed_req = set(log["first_shed_t"])
    o["serving_load_shed_rate"] = round(len(shed_req) / NREQ, 4)
    o["serving_load_shed_attempts"] = log["shed_attempts"]
    o["serving_load_dropped_requests"] = len(set(log["dropped"]))
    for cls in ("interactive", "standard", "batch"):
        n = lm.counters.get(f"serving_shed_total:{cls}", 0)
        if n:
            o[f"serving_load_shed_total_{cls}"] = n
    o["serving_load_deadline_miss_total"] = lm.counters.get(
        "serving_deadline_miss_total", 0
    )
    o["serving_load_preempt_total"] = lm.counters.get(
        "serving_preempt_total", 0
    )
    # retry-after honesty: over requests that were shed and later
    # admitted, observed wait-to-admission vs the FIRST advertised
    # retry-after (a client that waited what it was told, then got in)
    ratios = [
        (log["admit_t"][i] - log["first_shed_t"][i]) / log["advertised"][i]
        for i in log["admit_t"]
        if log["advertised"].get(i)
    ]
    if ratios:
        o["serving_load_retry_after_honesty"] = round(
            float(np.median(ratios)), 3
        )
        o["serving_load_retry_after_advertised_s"] = round(
            float(np.median(list(log["advertised"].values()))), 4
        )
    if ttft_un is not None and ttft_un.n:
        un99 = ttft_un.quantile(0.99)
        o["serving_load_interactive_uncontended_ttft_p99_s"] = round(
            un99, 5
        )
        lo99 = o.get("serving_load_interactive_ttft_p99_s")
        if lo99 and un99 > 0:
            # the headline SLO claim: protected traffic degrades
            # bounded (< 2x) while BATCH absorbs the shedding
            o["serving_load_interactive_p99_degradation"] = round(
                lo99 / un99, 3
            )

    # marginal cost of the admission features at 1x load (no sheds, no
    # chaos): identical traffic submitted WITH priority+deadline vs
    # plain — the serving_timing_overhead_frac-style < 1% key
    subs_plain = [(p_, {}) for p_ in prompts[:2 * SLOTS]]
    subs_slo = [
        (p_, {"priority": prios[j], "deadline_s": 120.0})
        for j, p_ in enumerate(prompts[:2 * SLOTS])
    ]
    s1 = new_sched(Metrics())
    t0 = time.perf_counter()
    n1 = pump_all(s1, subs_slo)
    slo_tps = n1 / (time.perf_counter() - t0)
    s2 = new_sched(Metrics())
    t0 = time.perf_counter()
    n2 = pump_all(s2, subs_plain)
    plain_tps = n2 / (time.perf_counter() - t0)
    o["serving_load_admission_overhead_frac"] = round(
        max(1.0 - slo_tps / plain_tps, 0.0), 4
    )
    o["serving_load_config"] = (
        f"GPT-2 small bf16 paged, {NREQ} Poisson arrivals (P{P_} "
        f"N{N_}) at {OVERSUB}x measured capacity over {SLOTS} slots "
        f"(25/25/50 interactive/standard/batch), max_queue {SLOTS}, "
        f"one {KILL_STALL_S}s chaos stall at arrival {KILL_AT}; shed "
        "clients honor retry_after_s with <= 4 retries"
    )
    return o


def observability_round() -> dict:
    """Telemetry cost round (ISSUE 16): the same loaded serving
    traffic pumped with the FULL observability stack on (metrics +
    ring-buffer sampler + SLO alert evaluation at 10 Hz — ten times
    the production 1 Hz cadence, so the reported fraction is an upper
    bound) vs metrics-only, plus the wall cost of one validator
    ``GET /fleet`` poll over a populated 3-node fleet table. Both keys
    are lower-better (``tldiag bench-diff`` classifies them from the
    ``overhead_frac`` / ``_s`` suffixes)."""
    import asyncio
    import threading
    from types import SimpleNamespace

    from tensorlink_tpu.config import MeshConfig
    from tensorlink_tpu.models.gpt2 import GPT2, GPT2Config
    from tensorlink_tpu.parallel.inference import (
        GenerationConfig,
        InferenceEngine,
    )
    from tensorlink_tpu.parallel.serving import (
        PagedContinuousBatchingEngine,
    )
    from tensorlink_tpu.runtime.alerts import AlertEngine, default_rules
    from tensorlink_tpu.runtime.http_status import StatusServer
    from tensorlink_tpu.runtime.mesh import make_mesh
    from tensorlink_tpu.runtime.metrics import Metrics
    from tensorlink_tpu.runtime.timeseries import (
        FleetStore,
        TimeSeriesStore,
    )

    P_, N_, SLOTS, NREQ, REPS = 32, 32, 8, 24, 3
    SAMPLE_S = 0.1  # 10x the production timeseries_interval_s default
    ocfg = GPT2Config(qkv_fused=True)
    omodel = GPT2(ocfg)
    oeng = InferenceEngine(
        make_mesh(MeshConfig()), omodel, omodel.init(jax.random.key(0)),
        max_len=256,
    )
    gen = GenerationConfig(max_new_tokens=N_)
    prompts = np.random.default_rng(11).integers(
        0, ocfg.vocab_size, (NREQ, P_)
    )

    def run_once(with_ts: bool) -> float:
        m = Metrics()
        sch = PagedContinuousBatchingEngine(
            oeng, slots=SLOTS, gen=gen, decode_chunk=8, block_size=16,
            prefill_chunk=32, max_queue=NREQ, prefix_cache=True,
            metrics=m, warm_buckets=True,
        )
        stop = threading.Event()
        sampler = None
        if with_ts:
            ts = TimeSeriesStore()
            alert_eng = AlertEngine(default_rules(), metrics=m)

            def loop() -> None:
                while not stop.wait(SAMPLE_S):
                    ts.sample_metrics(m)
                    sch.kv_stats_summary()
                    alert_eng.evaluate(ts)

            sampler = threading.Thread(target=loop, daemon=True)
            sampler.start()
        t0 = time.perf_counter()
        rids = [sch.submit(p_) for p_ in prompts]
        sch.run_until_idle()
        ntok = sum(len(sch.result(r_)) for r_ in rids)
        dt = time.perf_counter() - t0
        stop.set()
        if sampler is not None:
            sampler.join(timeout=2.0)
        return ntok / dt

    run_once(False)  # warm the buckets once for both arms
    # interleave the arms so drift (thermal, page cache) hits both
    tps_on = max(run_once(True) for _ in range(REPS))
    tps_off = max(run_once(False) for _ in range(REPS))
    o: dict = {
        "observability_overhead_frac": round(
            max(1.0 - tps_on / tps_off, 0.0), 4
        ),
    }

    # one validator /fleet poll over a 3-node fleet table populated to
    # the heartbeat-delta clamps (the realistic steady-state size)
    fs = FleetStore()
    base_t = time.time() - 600.0
    names = [
        "serving_ttft_s.p99", "serving_tpot_s.p99", "serving_ttft_s.count",
        "kv_pool_utilization", "kv_blocks_in_use", "serving_requests_total",
        "serving_shed_total", "host_gap_frac",
    ]
    for nid in ("node-a", "node-b", "node-c"):
        for lo in range(0, 600, 20):  # <= 160 points per delta (clamp)
            delta = {
                "t": base_t + lo,
                "series": {
                    name: {
                        "kind": "counter" if name.endswith("_total")
                        or name.endswith(".count") else "gauge",
                        "points": [
                            [base_t + lo + k, float((lo + k) % 97)]
                            for k in range(20)
                        ],
                    }
                    for name in names
                },
            }
            fs.ingest(nid, delta, kv={"occupancy": 0.5, "chains": 4})

    async def poll() -> float:
        from tensorlink_tpu.diag import http_get

        server = StatusServer(
            SimpleNamespace(fleet_series=fs), "127.0.0.1", 0
        )
        await server.start()
        try:
            port = server.bound_port
            best = float("inf")
            for _ in range(5):
                t0 = time.perf_counter()
                status, body = await http_get("127.0.0.1", port, "/fleet")
                dt = time.perf_counter() - t0
                assert status == 200 and body
                best = min(best, dt)
            return best
        finally:
            await server.stop()

    o["fleet_scrape_s"] = round(asyncio.run(poll()), 5)
    o["observability_config"] = (
        f"GPT-2 small bf16 paged, {NREQ} reqs (P{P_} N{N_}) over "
        f"{SLOTS} slots; sampler+alerts at {SAMPLE_S}s vs off, best of "
        f"{REPS}; /fleet poll over 3 nodes x {len(names)} series x 600s"
    )
    return o


def metering_round() -> dict:
    """Work-receipt metering cost round (ISSUE 19): the same loaded
    continuous-batching traffic with per-request metering ON (engine
    accumulators + canonical-bytes receipt signing for every finished
    request, exactly what a worker does on the serve path) vs metering
    compiled out. Also reports the wall cost of signing one receipt
    and of one auditor verify+ingest. ``metering_overhead_frac`` is
    the acceptance number (< 0.01); lower-better via the
    ``overhead_frac`` / ``_s`` suffixes ``tldiag bench-diff`` keys on."""
    from tensorlink_tpu.config import MeshConfig
    from tensorlink_tpu.models.gpt2 import GPT2, GPT2Config
    from tensorlink_tpu.parallel.inference import (
        GenerationConfig,
        InferenceEngine,
    )
    from tensorlink_tpu.parallel.serving import (
        PagedContinuousBatchingEngine,
    )
    from tensorlink_tpu.p2p.crypto import Identity
    from tensorlink_tpu.runtime.ledger import (
        ReceiptAuditor,
        build_receipt,
    )
    from tensorlink_tpu.runtime.mesh import make_mesh

    P_, N_, SLOTS, NREQ, REPS = 32, 32, 8, 24, 3
    mcfg = GPT2Config(qkv_fused=True)
    mmodel = GPT2(mcfg)
    meng = InferenceEngine(
        make_mesh(MeshConfig()), mmodel, mmodel.init(jax.random.key(0)),
        max_len=256,
    )
    gen = GenerationConfig(max_new_tokens=N_)
    prompts = np.random.default_rng(13).integers(
        0, mcfg.vocab_size, (NREQ, P_)
    )
    ident = Identity.generate()

    def run_once(metered: bool) -> tuple[float, int]:
        sch = PagedContinuousBatchingEngine(
            meng, slots=SLOTS, gen=gen, decode_chunk=8, block_size=16,
            prefill_chunk=32, max_queue=NREQ, prefix_cache=True,
            warm_buckets=True, metering=metered,
        )
        nrec = 0
        t0 = time.perf_counter()
        rids = [sch.submit(p_) for p_ in prompts]
        sch.run_until_idle()
        ntok = sum(len(sch.result(r_)) for r_ in rids)
        if metered:  # sign inside the timed region — it's serve-path work
            receipts = [
                build_receipt(m_, ident) for m_ in sch.drain_meters(NREQ)
            ]
            nrec = len(receipts)
        return ntok / (time.perf_counter() - t0), nrec

    run_once(False)  # warm buckets for both arms
    # interleave the arms so drift (thermal, page cache) hits both
    on = [run_once(True) for _ in range(REPS)]
    tps_off = max(run_once(False)[0] for _ in range(REPS))
    tps_on = max(t_ for t_, _ in on)
    o: dict = {
        "metering_overhead_frac": round(
            max(1.0 - tps_on / tps_off, 0.0), 4
        ),
        "metering_receipts_per_request": round(
            sum(n_ for _, n_ in on) / (REPS * NREQ), 3
        ),
    }

    # microcosts: one canonical-bytes sign, one auditor verify+ingest
    meter = {
        "schema": 1, "rid": 1, "tenant": "bench", "kind": "serve",
        "t_start": 100.0, "t_end": 101.0, "prompt_tokens": P_,
        "emitted_tokens": N_, "busy_s": 0.5, "flops": 1e9,
        "hbm_bytes": 1e8, "kv_block_s": 3.0, "wire_bytes": 128,
    }
    t0 = time.perf_counter()
    K = 200
    for i in range(K):
        build_receipt({**meter, "rid": i}, ident)
    o["receipt_sign_s"] = round((time.perf_counter() - t0) / K, 6)
    aud = ReceiptAuditor()
    batch = [build_receipt({**meter, "rid": i}, ident) for i in range(K)]
    t0 = time.perf_counter()
    for r_ in batch:
        aud.ingest(r_)
    o["receipt_audit_s"] = round((time.perf_counter() - t0) / K, 6)
    assert aud.accepted_total == K, "bench receipts must verify"
    o["metering_config"] = (
        f"GPT-2 small bf16 paged, {NREQ} reqs (P{P_} N{N_}) over "
        f"{SLOTS} slots; metering+signing vs metering=False, best of "
        f"{REPS}; microcosts averaged over {K} receipts"
    )
    return o


def main() -> None:
    devices = backend_with_retry()
    device_kind = devices[0].device_kind
    peak = peak_tflops_for(device_kind)

    cfg, state, batch, one_step, multi_step = build(BATCH, SEQ)
    call_dt, (state, multi_compiled) = measure(state, batch, multi_step)
    steps_per_sec = STEPS_PER_CALL / call_dt
    # the un-sharded jit step runs on exactly one chip regardless of how
    # many the host exposes
    samples_per_sec_per_chip = BATCH * steps_per_sec

    # -- FLOPs, both ways, cross-checked --------------------------------
    analytic = analytic_step_flops(state.params, cfg, BATCH, SEQ)
    xla, xla_bytes = xla_step_cost(one_step, state, batch)
    flops_per_step, flops_src = (xla, "xla_cost_analysis") if xla else (
        analytic, "analytic_6PT+attn")
    consistent = xla is None or (0.5 <= xla / analytic <= 2.0)
    achieved_tflops = flops_per_step * steps_per_sec / 1e12
    mfu = achieved_tflops / peak if peak else None

    out = {
        "metric": f"samples/sec/chip (BERT-{_BERT} fine-tune, batch {BATCH}, seq {SEQ}, bf16)",
        "value": round(samples_per_sec_per_chip, 2),
        "unit": "samples/sec/chip",
        "device_kind": device_kind,
        "achieved_tflops": round(achieved_tflops, 2),
        "peak_bf16_tflops": peak,
        "mfu": round(mfu, 4) if mfu is not None else None,
        "flops_source": flops_src,
        "flops_per_step_xla": xla,
        "flops_per_step_analytic": analytic,
    }
    if not consistent:
        out["error"] = (
            f"flops cross-check failed: xla={xla:.3e} vs analytic="
            f"{analytic:.3e} disagree by more than 2x"
        )

    # -- roofline: is the residual MFU gap compute or bandwidth?
    # (VERDICT r3 weak #3 ask: push past 0.49 or prove the ceiling)
    hbm = hbm_gbps_for(device_kind)
    if peak and hbm and xla_bytes:
        from tensorlink_tpu.runtime.profiling import roofline

        out["roofline"] = {
            k: (round(v, 6) if isinstance(v, float) else v)
            for k, v in roofline(
                flops_per_step=flops_per_step,
                hbm_bytes_per_step=xla_bytes,
                peak_tflops=peak,
                hbm_gbps=hbm,
                measured_step_s=1.0 / steps_per_sec,
            ).items()
        }

    # -- on-chip op profile as an ARTIFACT (VERDICT r4 weak #7: the
    # 83.8%-matmul-fusion figure anchoring the MFU-ceiling argument
    # lived only in BASELINE.md prose). One profiled multi-step call of
    # the already-warm headline program.
    if os.environ.get("BENCH_PROFILE", "1") == "1" and _BERT == "base":
        try:
            from tensorlink_tpu.runtime.profiling import op_breakdown

            prof = op_breakdown(lambda: multi_compiled(state, batch)[1])
            out["op_breakdown"] = {
                "device_s_per_call": round(prof["total_s"], 4),
                "steps_per_call": STEPS_PER_CALL,
                "top": {
                    c: round(d["fraction"], 3)
                    for c, d in list(prof["categories"].items())[:5]
                },
            }
        except Exception as e:  # noqa: BLE001
            out["op_breakdown_error"] = str(e)[:200]
        finally:
            # the profiled call DONATED state's buffers (multi_step has
            # donate_argnums=(0,)); unbind so nothing downstream can
            # read deleted arrays
            state = None

    def mfu_of(flops_step: float, steps_per_s: float) -> float | None:
        """One formula for every secondary measurement (drift guard)."""
        return (
            round(flops_step * steps_per_s / 1e12 / peak, 4) if peak else None
        )

    # -- batch sweep at the headline seq: a memory/overhead-bound program
    # gains from larger batches, a compute-bound one saturates
    if os.environ.get("BENCH_SWEEP", "1") == "1" and _BERT == "base":
        sweep = {str(BATCH): round(samples_per_sec_per_chip, 2)}
        for b2 in (64, 128):
            if b2 == BATCH:
                continue  # headline batch already measured above
            try:
                _, st2, ba2, one2, multi2 = build(b2, SEQ)
                dt2, _ = measure(st2, ba2, multi2)
                sps2 = b2 * STEPS_PER_CALL / dt2
                sweep[str(b2)] = round(sps2, 2)
                f2, _ = xla_step_cost(one2, st2, ba2)
                if f2 and peak:
                    sweep[f"mfu@{b2}"] = mfu_of(f2, STEPS_PER_CALL / dt2)
            except Exception as e:  # noqa: BLE001 — OOM at 128 is fine
                sweep[str(b2)] = f"error: {str(e)[:80]}"
        out["batch_sweep_samples_per_sec"] = sweep

    # -- bf16 optimizer moments at the headline shape: the roofline says
    # batch 32 is memory-bound and m+v are a third of the state bytes —
    # this measures what halving them buys (opt_moment_dtype feature)
    if os.environ.get("BENCH_BF16_MOM", "1") == "1" and _BERT == "base":
        try:
            _, stm, bam, onem, multim = build(
                BATCH, SEQ, moment_dtype="bfloat16"
            )
            dtm, _ = measure(stm, bam, multim)
            spsm = BATCH * STEPS_PER_CALL / dtm
            out["bf16_moments_samples_per_sec"] = round(spsm, 2)
            fm, _ = xla_step_cost(onem, stm, bam)
            if fm and peak:
                out["bf16_moments_mfu"] = mfu_of(fm, STEPS_PER_CALL / dtm)
        except Exception as e:  # noqa: BLE001 — must not sink the headline
            out["bf16_moments_error"] = str(e)[:200]

    # -- secondary: seq 512 where attention carries real weight ---------
    if _LONG and _BERT == "base":
        # seq 512 now runs the Pallas flash path through attn_impl="auto"
        # (MIN_KERNEL_SEQ_AUTO dropped to 512 after the r5 re-sweep:
        # kernel 1.09-1.25x over the einsum step at this shape). FLOPs
        # come from the ANALYTIC count: cost_analysis does not see inside
        # pallas_call, so the xla number under-reports the flash program
        # by ~1.2x and its "MFU" would silently flatter nothing (r5
        # finding; the xla count is kept as a cross-check field).
        s512 = 512
        sweep512 = {}
        for b512 in (8, 64):
            cfg2, st2, ba2, one2, multi2 = build(b512, s512)
            dt2, _ = measure(st2, ba2, multi2)
            sps2 = STEPS_PER_CALL / dt2
            fl2 = analytic_step_flops(st2.params, cfg2, b512, s512)
            sweep512[str(b512)] = {
                "samples_per_sec_per_chip": round(b512 * sps2, 2),
                "mfu": mfu_of(fl2, sps2),
            }
            if b512 == 8:
                out["seq512_samples_per_sec_per_chip"] = round(b512 * sps2, 2)
                out["seq512_mfu"] = mfu_of(fl2, sps2)
                # the MFU plateau, first-class under BOTH accountings
                # (VERDICT #7): analytic is the honest number for the
                # flash program (cost_analysis can't see inside
                # pallas_call), xla is exact for what XLA itself emitted
                # — reporting only one buried the gap in a footnote
                xla512 = xla_step_cost(one2, st2, ba2)[0]
                out["seq512_mfu_analytic"] = out["seq512_mfu"]
                out["seq512_mfu_xla"] = (
                    mfu_of(xla512, sps2) if xla512 else None
                )
                out["seq512_flops_xla_crosscheck"] = xla512
        out["seq512_batch_sweep"] = sweep512

    # -- secondary: KV-cache decode throughput (BASELINE.json names
    # sharded inference as a north-star config; this is the single-chip
    # engine measurement). Failure-tolerant: a decode-path problem must
    # not sink the headline metric.
    if os.environ.get("BENCH_DECODE", "1") == "1" and _BERT == "base":
        try:
            from tensorlink_tpu.config import MeshConfig
            from tensorlink_tpu.models.gpt2 import GPT2, GPT2Config
            from tensorlink_tpu.parallel.inference import (
                GenerationConfig,
                InferenceEngine,
            )
            from tensorlink_tpu.runtime.mesh import make_mesh

            B, P, N = 8, 32, 64
            gcfg = GPT2Config(qkv_fused=True)  # small (124M), fused q/k/v
            gmodel = GPT2(gcfg)
            # engine casts params to bf16 itself; the 2048-capacity engine
            # allocates THIS program's cache at the tight static horizon
            # (P + N block-rounded = 256 slots), so decode runs one
            # full-width attention per layer with no bounded-loop launches
            eng = InferenceEngine(
                make_mesh(MeshConfig()), gmodel,
                gmodel.init(jax.random.key(0)), max_len=2048,
            )
            r = np.random.default_rng(0)
            pids = jnp.asarray(r.integers(0, gcfg.vocab_size, (B, P)))
            gen = GenerationConfig(max_new_tokens=N)
            toks = eng.generate(pids, gen)  # compile + first call
            int(np.asarray(toks)[0, -1])
            # serialized calls: each pays a full host->device RTT (the
            # r4 methodology — kept for comparability)
            t0 = time.perf_counter()
            reps = 3
            for _ in range(reps):
                toks = eng.generate(pids, gen)
            int(np.asarray(toks)[0, -1])
            dt = (time.perf_counter() - t0) / reps
            out["decode_tokens_per_sec_serial"] = round(B * N / dt, 1)
            # steady-state serving: back-to-back requests pipeline
            # through the dispatch queue (generate_async), one sync at
            # the end — how a serving loop actually drives the chip
            reps = 8
            t0 = time.perf_counter()
            outs = [eng.generate_async(pids, gen) for _ in range(reps)]
            int(np.asarray(outs[-1])[0, -1])
            dt = (time.perf_counter() - t0) / reps
            out["decode_tokens_per_sec"] = round(B * N / dt, 1)
            out["decode_config"] = (
                f"GPT-2 small bf16 KV-cache qkv_fused, batch {B}, prompt "
                f"{P}, {N} new tokens; steady-state = {reps} pipelined "
                "calls, single sync (serial field = per-call sync)"
            )
            # decode roofline: weight-streaming + KV bytes per step over
            # the v5e HBM floor. Weights: every matmul weight streams
            # once per token step (wte counted once — the tied head
            # matmul; the embed side is an 8-row gather); KV: full-width
            # attention reads the tight-allocated cache per layer.
            wbytes, cbytes, bound = decode_roofline(
                eng.params, hbm, gcfg.num_layers, B, P, N,
                kv_head_dim=gcfg.dim,  # GPT-2: Hkv == H, kv dim == dim
            )
            if bound:
                out["decode_roofline"] = {
                    "weight_bytes_per_step": wbytes,
                    "kv_bytes_per_step": cbytes,
                    "bandwidth_bound_tokens_per_sec": round(bound, 1),
                    "fraction_attained": round(
                        out["decode_tokens_per_sec"] / bound, 3
                    ),
                }
            if os.environ.get("BENCH_PROFILE", "1") == "1":
                # op-level evidence (VERDICT r4 weak #7): per-HLO-category
                # device time of one pipelined decode call
                from tensorlink_tpu.runtime.profiling import op_breakdown

                prof = op_breakdown(
                    lambda: eng.generate_async(pids, gen)
                )
                out["decode_op_breakdown"] = {
                    "device_s_per_call": round(prof["total_s"], 4),
                    "top": {
                        c: round(d["fraction"], 3)
                        for c, d in list(prof["categories"].items())[:5]
                    },
                }
        except Exception as e:  # noqa: BLE001
            out["decode_error"] = str(e)[:200]

    # -- continuous batching vs static batching (ISSUE 5 tentpole):
    # N staggered prompts through the fixed-slot scheduler vs the same
    # prompts in one static generate() batch. The acceptance bar is
    # continuous >= 0.9x static aggregate tok/s WITH per-request
    # TTFT/TPOT measured (the static batch has no per-request story at
    # all: every request waits for the whole batch).
    if os.environ.get("BENCH_SERVING_CB", "1") == "1" and _BERT == "base":
        try:
            from tensorlink_tpu.config import MeshConfig
            from tensorlink_tpu.models.gpt2 import GPT2, GPT2Config
            from tensorlink_tpu.parallel.inference import (
                GenerationConfig,
                InferenceEngine,
            )
            from tensorlink_tpu.parallel.serving import (
                ContinuousBatchingEngine,
            )
            from tensorlink_tpu.runtime.mesh import make_mesh
            from tensorlink_tpu.runtime.metrics import Metrics

            # slot width == static batch width: the ratio then isolates
            # the scheduler's own overheads (chunked dispatch, batch-1
            # prefills) from batch-size efficiency on a memory-bound
            # decode, which slots < batch would conflate
            Pcb, Ncb, NREQ, SLOTS = 32, 64, 16, 16
            cbcfg = GPT2Config(qkv_fused=True)
            cbmodel = GPT2(cbcfg)
            cbeng = InferenceEngine(
                make_mesh(MeshConfig()), cbmodel,
                cbmodel.init(jax.random.key(0)), max_len=256,
            )
            rcb = np.random.default_rng(0)
            cbprompts = rcb.integers(0, cbcfg.vocab_size, (NREQ, Pcb))
            cbgen = GenerationConfig(max_new_tokens=Ncb)

            # static figure: ALL prompts as one batch (static batching's
            # best case), warm + 3 reps
            sids = jnp.asarray(cbprompts)
            t = cbeng.generate(sids, cbgen)
            int(np.asarray(t)[0, -1])
            t0 = time.perf_counter()
            for _ in range(3):
                t = cbeng.generate_async(sids, cbgen)
            int(np.asarray(t)[0, -1])
            static_tps = NREQ * Ncb / ((time.perf_counter() - t0) / 3)

            # chip capability microbench (runtime/profiling.py): the
            # peaks per-program MFU/MBU normalize against — measured,
            # not a spec-sheet constant
            from tensorlink_tpu.runtime.profiling import (
                measure_capability,
            )

            cap = measure_capability()
            out["capability_peak_tflops"] = cap["peak_tflops"]
            out["capability_hbm_gbps"] = cap["hbm_gbps"]

            # warm_buckets: the AOT compiles also capture each
            # program's XLA cost analysis, the flops/bytes numerators
            # of the per-dispatch MFU/MBU reported below
            sch = ContinuousBatchingEngine(
                cbeng, slots=SLOTS, gen=cbgen, decode_chunk=16,
                prefill_block=32, capability=cap, warm_buckets=True,
            )
            # warm round compiles prefill bucket + decode chunk; the
            # metrics registry is attached AFTER it so the published
            # TTFT/TPOT quantiles measure serving, not XLA compiles
            for p_ in cbprompts[:SLOTS]:
                sch.submit(p_)
            sch.run_until_idle()
            sch.metrics = cbm = Metrics()
            t0 = time.perf_counter()
            rids = [sch.submit(p_) for p_ in cbprompts]
            sch.run_until_idle()
            dt = time.perf_counter() - t0
            ntok = sum(len(sch.result(rid)) for rid in rids)
            cont_tps = ntok / dt
            out["serving_continuous_tokens_per_sec"] = round(cont_tps, 1)
            out["serving_static_tokens_per_sec"] = round(static_tps, 1)
            out["serving_continuous_vs_static"] = round(
                cont_tps / static_tps, 3
            )
            th = cbm.histograms.get("serving_ttft_s")
            tp = cbm.histograms.get("serving_tpot_s")
            if th is not None:
                out["serving_ttft_p50_s"] = round(th.quantile(0.5), 5)
                out["serving_ttft_p99_s"] = round(th.quantile(0.99), 5)
            if tp is not None:
                out["serving_tpot_p50_s"] = round(tp.quantile(0.5), 6)
                out["serving_tpot_p99_s"] = round(tp.quantile(0.99), 6)
            out["serving_cb_config"] = (
                f"GPT-2 small bf16 qkv_fused, {NREQ} staggered prompts "
                f"(P{Pcb} N{Ncb}) over {SLOTS} slots, decode_chunk 16, "
                "vs the same prompts in one static batch"
            )

            # -- always-on device-time attribution (ISSUE 13
            # tentpole): per-program device-busy vs host-gap from the
            # drains the round above already paid, with MFU/MBU
            # against the measured chip peaks — and the cost of the
            # telemetry itself, measured as tokens/sec against an
            # identical timing-DISABLED run (acceptance: < 1%)
            try:
                dtm = sch.device_time() or {}
                dprog = (dtm.get("programs") or {}).get("decode") or {}
                if dprog.get("mfu") is not None:
                    out["decode_mfu"] = dprog["mfu"]
                if dprog.get("mbu") is not None:
                    out["decode_mbu"] = dprog["mbu"]
                out["serving_host_gap_frac"] = dtm.get("host_gap_frac")
                # IDENTICAL construction/warm/metrics flow except the
                # timer — anything else (AOT vs lazy jit, metrics
                # observes) would land in the overhead key and be
                # blamed on the telemetry
                sch_off = ContinuousBatchingEngine(
                    cbeng, slots=SLOTS, gen=cbgen, decode_chunk=16,
                    prefill_block=32, capability=cap, warm_buckets=True,
                    device_timing=False,
                )
                for p_ in cbprompts[:SLOTS]:
                    sch_off.submit(p_)
                sch_off.run_until_idle()
                sch_off.metrics = Metrics()
                t0 = time.perf_counter()
                orids = [sch_off.submit(p_) for p_ in cbprompts]
                sch_off.run_until_idle()
                odt = time.perf_counter() - t0
                otok = sum(len(sch_off.result(r_)) for r_ in orids)
                off_tps = otok / odt
                out["serving_timing_disabled_tokens_per_sec"] = round(
                    off_tps, 1
                )
                out["serving_timing_overhead_frac"] = round(
                    1.0 - cont_tps / off_tps, 4
                )
            except Exception as e:  # noqa: BLE001
                out["serving_devtime_error"] = str(e)[:200]
            # ON-DEVICE donation evidence (tlhlo TLH101, the backend
            # actually benched — the committed hlo.manifest.json pins
            # the CPU lowering): every donated serving-state leaf must
            # alias an output or the engine pays a full state copy per
            # chunk, which would silently poison every number above
            try:
                from tensorlink_tpu.analysis.hlo import parse_alias_count

                decode_prog = sch.audit_programs()[0]
                aliased = parse_alias_count(
                    decode_prog["lower"]().compile().as_text()
                )
                donated = decode_prog["donated"]
                out["serving_decode_donated_leaves"] = donated
                out["serving_decode_aliased_leaves"] = aliased
                if aliased < donated:
                    out["serving_decode_donation_dropped"] = True
            except Exception as e:  # noqa: BLE001 — evidence, not gate
                out["serving_decode_donation_note"] = (
                    f"{type(e).__name__}: {e}"
                )

            # -- paged KV cache (ISSUE 6 tentpole): the same traffic
            # volume but every request opens with one shared 64-token
            # system prompt — the million-user workload the prefix
            # cache exists for. Reported: prefix hit rate (>0 == the
            # sharing works), prefilled tokens vs the contiguous
            # engine (drops by the hit tokens), peak blocks in use
            # (HBM scales with LIVE tokens, not slots x max_len), and
            # aggregate tok/s vs the contiguous scheduler.
            try:
                from tensorlink_tpu.parallel.serving import (
                    PagedContinuousBatchingEngine,
                )

                SYS = 64
                psys = rcb.integers(0, cbcfg.vocab_size, (SYS,))
                pgprompts = [
                    np.concatenate(
                        [psys, rcb.integers(0, cbcfg.vocab_size, (Pcb,))]
                    )
                    for _ in range(NREQ)
                ]
                psch = PagedContinuousBatchingEngine(
                    cbeng, slots=SLOTS, gen=cbgen, decode_chunk=16,
                    block_size=16, prefill_chunk=64, capability=cap,
                )
                # warm round: compile + seed the prefix index so the
                # measured round's hit rate reflects steady state
                psch.result(psch.submit(pgprompts[0]))
                warm_matched = psch.prefix_matched_tokens
                warm_prompt = psch.prompt_tokens_total
                warm_prefilled = psch.prefilled_tokens
                psch.peak_blocks_in_use = psch.pool.in_use
                t0 = time.perf_counter()
                prids = [psch.submit(p_) for p_ in pgprompts]
                psch.run_until_idle()
                dt = time.perf_counter() - t0
                ptok = sum(len(psch.result(rid)) for rid in prids)
                paged_tps = ptok / dt
                pool = psch.pool
                matched = psch.prefix_matched_tokens - warm_matched
                prompt_tok = psch.prompt_tokens_total - warm_prompt
                out["serving_paged_tokens_per_sec"] = round(paged_tps, 1)
                out["serving_paged_vs_continuous"] = round(
                    paged_tps / cont_tps, 3
                )
                out["prefix_cache_hit_rate"] = round(
                    matched / prompt_tok, 4
                )
                out["kv_blocks_in_use"] = psch.peak_blocks_in_use
                out["kv_pool_utilization"] = round(
                    psch.peak_blocks_in_use / pool.num_blocks, 4
                )
                # prompt tokens actually run through prefill programs:
                # the contiguous engine re-prefills every prompt in
                # full, the paged engine skips resident prefix blocks
                out["serving_paged_prefilled_tokens"] = (
                    psch.prefilled_tokens - warm_prefilled
                )
                out["serving_contiguous_prefilled_tokens"] = prompt_tok
                # HBM the cache would pin, paged (live blocks) over
                # contiguous (slots x max_len), same dtype/layers
                out["kv_footprint_vs_contiguous"] = round(
                    psch.peak_blocks_in_use * psch.block_size
                    / (SLOTS * cbeng.cache_len), 4
                )
                out["serving_paged_config"] = (
                    f"shared {SYS}-token system prompt + {Pcb} unique, "
                    f"{NREQ} requests over {SLOTS} slots, block_size 16, "
                    f"prefill_chunk 64, pool {pool.num_blocks} blocks"
                )
                # decode MBU on the paged XLA gather path — the
                # "before kernel" side of the ISSUE 20 pair
                pdt = psch.device_time() or {}
                pprog = (pdt.get("programs") or {}).get("decode") or {}
                if pprog.get("mbu") is not None:
                    out["decode_mbu_paged_xla"] = pprog["mbu"]

                # -- int8 KV blocks (ISSUE 20): the same traffic on
                # quantized pools. The footprint ratio goes BYTE-aware
                # here: int8 blocks + f32 scale siblings in use vs the
                # contiguous cache (slots x max_len at the float
                # engine's per-token width) the engine would otherwise
                # pin — the ~2x HBM win int8 exists for.
                try:
                    psq = PagedContinuousBatchingEngine(
                        cbeng, slots=SLOTS, gen=cbgen, decode_chunk=16,
                        block_size=16, prefill_chunk=64,
                        kv_quant="int8",
                    )
                    psq.result(psq.submit(pgprompts[0]))
                    psq.peak_blocks_in_use = psq.pool.in_use
                    t0 = time.perf_counter()
                    qrids = [psq.submit(p_) for p_ in pgprompts]
                    psq.run_until_idle()
                    qdt = time.perf_counter() - t0
                    qtok = sum(len(psq.result(rid)) for rid in qrids)
                    out["serving_paged_int8_tokens_per_sec"] = round(
                        qtok / qdt, 1
                    )
                    contig_bytes = (
                        SLOTS * cbeng.cache_len
                        * psch.kv_block_bytes / psch.block_size
                    )
                    out["kv_footprint_vs_contiguous_int8"] = round(
                        psq.peak_blocks_in_use * psq.kv_block_bytes
                        / contig_bytes, 4
                    )
                except Exception as e:  # noqa: BLE001
                    out["serving_paged_int8_error"] = str(e)[:200]

                # -- paged-decode kernel vs the XLA gather path (ISSUE
                # 20 tentpole): the same engine geometry decoding a
                # deliberately tiny workload twice — TL_PAGED_KERNEL=0
                # vs the Pallas kernel. Off-TPU the kernel runs in
                # interpret-mode EMULATION, so the ratio prices the
                # emulator (< 1.0 expected) while still proving token
                # parity end-to-end; on a TPU backend the same key
                # reports the real fused-kernel speedup.
                try:
                    KP, KN, KREQ, KSLOTS = 16, 8, 4, 4
                    kprompts = [
                        rcb.integers(0, cbcfg.vocab_size, (KP,))
                        for _ in range(KREQ)
                    ]
                    kgen = GenerationConfig(max_new_tokens=KN)

                    def _kernel_run(mode):
                        prev = os.environ.get("TL_PAGED_KERNEL")
                        os.environ["TL_PAGED_KERNEL"] = mode
                        try:
                            ksch = PagedContinuousBatchingEngine(
                                cbeng, slots=KSLOTS, gen=kgen,
                                decode_chunk=4, block_size=16,
                                prefill_chunk=32, capability=cap,
                            )
                            ksch.result(ksch.submit(kprompts[0]))
                            t0 = time.perf_counter()
                            rids = [
                                ksch.submit(p_) for p_ in kprompts
                            ]
                            ksch.run_until_idle()
                            dt = time.perf_counter() - t0
                            toks = [
                                np.asarray(ksch.result(r_))
                                for r_ in rids
                            ]
                            tps = sum(len(t_) for t_ in toks) / dt
                            return ksch, toks, tps
                        finally:
                            if prev is None:
                                os.environ.pop("TL_PAGED_KERNEL", None)
                            else:
                                os.environ["TL_PAGED_KERNEL"] = prev

                    kmode = (
                        "1" if jax.default_backend() == "tpu"
                        else "interpret"
                    )
                    _, xtoks, x_tps = _kernel_run("0")
                    ksch, ktoks, k_tps = _kernel_run(kmode)
                    out["paged_kernel_vs_xla_tokens_per_sec"] = round(
                        k_tps / x_tps, 3
                    )
                    out["paged_kernel_token_parity"] = float(all(
                        np.array_equal(a, b)
                        for a, b in zip(xtoks, ktoks)
                    ))
                    kdt = ksch.device_time() or {}
                    kprog = (
                        (kdt.get("programs") or {}).get("decode") or {}
                    )
                    if kprog.get("mbu") is not None:
                        out["decode_mbu_paged_kernel"] = kprog["mbu"]
                    out["paged_kernel_config"] = (
                        f"{KREQ} requests (P{KP} N{KN}) over "
                        f"{KSLOTS} slots, block 16, "
                        f"TL_PAGED_KERNEL={kmode} vs 0"
                    )
                except Exception as e:  # noqa: BLE001
                    out["paged_kernel_error"] = str(e)[:200]
            except Exception as e:  # noqa: BLE001
                out["serving_paged_error"] = str(e)[:200]

            # -- speculative decoding (ISSUE 7 tentpole): the same
            # shared-prefix workload, decoded speculatively. Draft =
            # the target's OWN int8 weight-only sibling (the model
            # zoo's free draft pair: half the weight bytes per draft
            # step, and int8 provably preserves argmax almost always —
            # the int8_quality KL below measures exactly that), so
            # greedy acceptance is a REAL model property, not a
            # fixture. The headline is accepted_tokens_per_weight_pass:
            # > 1.0 means decode emits more than one token per full
            # weight read — past the bandwidth roofline that pins
            # decode_roofline.fraction_attained. The n-gram variant
            # (no draft model at all) rides the same verify program.
            try:
                from tensorlink_tpu.parallel.serving import SpecConfig

                SYSW = 64
                NSP, PSP, NNEW, SSL = 12, 24, 48, 6
                rsp = np.random.default_rng(7)
                sys_p = rsp.integers(0, cbcfg.vocab_size, (SYSW,))
                spprompts = [
                    np.concatenate(
                        [sys_p, rsp.integers(0, cbcfg.vocab_size, (PSP,))]
                    )
                    for _ in range(NSP)
                ]
                spgen = GenerationConfig(max_new_tokens=NNEW)

                def run_spec(draft_eng, spec_cfg):
                    s = ContinuousBatchingEngine(
                        cbeng, slots=SSL, gen=spgen, decode_chunk=16,
                        prefill_block=32, draft=draft_eng,
                        speculative=spec_cfg,
                    )
                    s.result(s.submit(spprompts[0]))  # warm/compile
                    t0 = time.perf_counter()
                    rids_ = [s.submit(p_) for p_ in spprompts]
                    s.run_until_idle()
                    dt_ = time.perf_counter() - t0
                    ntok_ = sum(len(s.result(r_)) for r_ in rids_)
                    return ntok_ / dt_, s.stats().get("spec")

                base_tps, _ = run_spec(None, None)  # non-spec baseline
                drafteng = InferenceEngine(
                    make_mesh(MeshConfig()), cbmodel, cbeng.params,
                    max_len=256, quantize="int8",
                )
                spec_tps, st = run_spec(drafteng, SpecConfig(k=4, rounds=2))
                out["accepted_tokens_per_weight_pass"] = st[
                    "accepted_tokens_per_weight_pass"
                ]
                out["spec_acceptance_rate"] = st["acceptance_rate"]
                out["spec_tokens_per_sec"] = round(spec_tps, 1)
                out["spec_vs_nonspec"] = round(spec_tps / base_tps, 3)
                ng_tps, ngst = run_spec(None, SpecConfig(k=4, rounds=2))
                out["spec_ngram_accepted_tokens_per_weight_pass"] = ngst[
                    "accepted_tokens_per_weight_pass"
                ]
                out["spec_ngram_acceptance_rate"] = ngst["acceptance_rate"]
                out["spec_ngram_tokens_per_sec"] = round(ng_tps, 1)
                out["spec_config"] = (
                    f"GPT-2 small bf16 target + int8 sibling draft "
                    f"(k=4, rounds=2), {NSP} requests (shared {SYSW} + "
                    f"{PSP} unique, {NNEW} new) over {SSL} slots, vs the "
                    "same engine/workload without speculation; ngram = "
                    "prompt-lookup self-speculation, same verify program"
                )

                # -- adaptive speculation (ISSUE 12 tentpole): per-
                # request masked K self-tuned from measured acceptance,
                # on a MIXED workload — half the requests continue a
                # repeated motif (draft-friendly: high acceptance, the
                # controller pushes K up), half are fresh random
                # prompts with small budgets (rejection-heavy rounds:
                # K shrinks to k_min and the entropy early-exit skips
                # the draft steps a static K would burn). One static K
                # cannot serve both halves; the headline is adaptive
                # wall-clock over the BEST static K on the identical
                # workload. The autotune store round-trips the learned
                # K prior + flash overrides (warm-start timing below).
                try:
                    import tempfile

                    from tensorlink_tpu.parallel.serving import (
                        autopair_draft,
                    )

                    rad = np.random.default_rng(21)
                    motif = rad.integers(0, cbcfg.vocab_size, (8,))
                    mixed = []
                    for i in range(NSP):
                        if i % 2 == 0:
                            p_ = np.concatenate(
                                [np.tile(motif, 6),
                                 rad.integers(0, cbcfg.vocab_size, (8,))]
                            )
                            mixed.append((p_, NNEW))
                        else:
                            mixed.append((
                                rad.integers(
                                    0, cbcfg.vocab_size, (PSP,)
                                ),
                                NNEW // 2,
                            ))
                    # temperature > 0 on purpose: greedy int8-draft
                    # acceptance is a near-constant model property, but
                    # under rejection sampling acceptance genuinely
                    # varies per request/position — the heterogeneity
                    # a per-request controller exists to exploit (and
                    # the output distribution stays exactly the
                    # target's at any K, so the comparison is fair)
                    adgen = GenerationConfig(
                        max_new_tokens=NNEW, temperature=0.7, top_p=0.95,
                    )

                    def run_adaptive(spec_cfg, autotune_dir=None):
                        s = ContinuousBatchingEngine(
                            cbeng, slots=SSL, gen=adgen, decode_chunk=16,
                            prefill_block=32, draft=drafteng,
                            speculative=spec_cfg,
                            autotune_dir=autotune_dir,
                        )
                        s.result(s.submit(mixed[0][0]))  # warm/compile
                        t0_ = time.perf_counter()
                        rids_ = [
                            s.submit(p_, max_new=m_) for p_, m_ in mixed
                        ]
                        s.run_until_idle()
                        dt_ = time.perf_counter() - t0_
                        ntok_ = sum(len(s.result(r_)) for r_ in rids_)
                        return ntok_ / dt_, s

                    static_best = 0.0
                    static_by_k = {}
                    for ks_ in (1, 2, 4):
                        k_tps, _ = run_adaptive(
                            SpecConfig(k=ks_, rounds=2)
                        )
                        static_by_k[str(ks_)] = round(k_tps, 1)
                        static_best = max(static_best, k_tps)
                    tune_dir = tempfile.mkdtemp(prefix="tl-autotune-")
                    ad_tps, ad_s = run_adaptive(
                        SpecConfig.auto(k=4, rounds=2),
                        autotune_dir=tune_dir,
                    )
                    ad_st = ad_s.stats()["spec"]
                    ad_s.save_autotune()
                    out["spec_adaptive_tokens_per_sec"] = round(ad_tps, 1)
                    out["spec_static_k_sweep_tokens_per_sec"] = static_by_k
                    out["spec_adaptive_vs_best_static"] = round(
                        ad_tps / static_best, 3
                    )
                    out["spec_k_mean"] = ad_st["k_mean"]
                    out["spec_adaptive_acceptance_rate"] = ad_st[
                        "acceptance_rate"
                    ]
                    # restart: a second engine over the same store must
                    # warm-start (flash overrides + K prior loaded, zero
                    # re-measurement) — the measured-constants side of
                    # the compile cache's restart story
                    _, warm_s = run_adaptive(
                        SpecConfig.auto(k=4, rounds=2),
                        autotune_dir=tune_dir,
                    )
                    out["autotune_warm_start_s"] = (
                        warm_s.autotune_warm_start_s
                    )
                    out["autotune_warm_k_prior"] = (
                        warm_s._autotune_record or {}
                    ).get("k_prior")
                    # measured draft pairing on this chip/model: which
                    # zoo candidate (or fallback mode) actually pays
                    verdict = autopair_draft(
                        cbeng, spgen, cfg=SpecConfig(k=4),
                        prompts=[p_ for p_, _ in mixed[:4]],
                    )
                    out["draft_autopair_choice"] = verdict["name"]
                    out["draft_autopair_measured"] = verdict["measured"]
                    out["spec_adaptive_config"] = (
                        f"mixed workload: {NSP} requests alternating "
                        f"48-token repeated-motif prompts (budget "
                        f"{NNEW}) and random {PSP}-token prompts "
                        f"(budget {NNEW // 2}), int8-sibling draft, "
                        "adaptive masked K (k_max 4, entropy exit, "
                        "self-heal) vs static K in {1, 2, 4}"
                    )
                except Exception as e:  # noqa: BLE001
                    out["spec_adaptive_error"] = str(e)[:200]
            except Exception as e:  # noqa: BLE001
                out["spec_error"] = str(e)[:200]
        except Exception as e:  # noqa: BLE001 — must not sink the headline
            out["serving_cb_error"] = str(e)[:200]

    # -- serving under load (ISSUE 14 tentpole): the "heavy traffic"
    # scenario made measurable. A Poisson-ish arrival process drives
    # ~4x slot oversubscription with mixed SLO classes through the
    # paged scheduler; a chaos-injected mid-run drain stall emulates a
    # worker kill / failover blackout. Reported: TTFT/TPOT p50/p99 PER
    # PRIORITY CLASS, shed rate, retry-after honesty (observed
    # successful-retry wait vs advertised), INTERACTIVE p99 vs its own
    # uncontended baseline, and the marginal cost of the admission
    # features at 1x load (priority+deadline submits vs plain ones —
    # the < 1% acceptance key).
    if os.environ.get("BENCH_LOAD", "1") == "1" and _BERT == "base":
        try:
            out.update(serving_under_load_round())
        except Exception as e:  # noqa: BLE001 — must not sink the headline
            out["serving_load_error"] = str(e)[:200]

    # -- disaggregated prefill/decode (ISSUE 15): paged KV blocks as
    # the wire unit between a prefill engine and a decode engine, vs
    # the same traffic colocated on one engine.
    if os.environ.get("BENCH_DISAGG", "1") == "1" and _BERT == "base":
        try:
            out.update(serving_disagg_round())
        except Exception as e:  # noqa: BLE001 — must not sink the headline
            out["serving_disagg_error"] = str(e)[:200]

    # -- pipeline-sharded serving (ISSUE 18): layer-sharded 3-stage
    # localhost mesh vs the same engine unsharded, with a parity pin
    if os.environ.get("BENCH_PIPELINE", "1") == "1" and _BERT == "base":
        try:
            out.update(serving_pipeline_round())
        except Exception as e:  # noqa: BLE001 — must not sink the headline
            out["pipeline_error"] = str(e)[:200]

    # -- observability cost (ISSUE 16): what the always-on ring
    # sampler + alert evaluation charges a loaded serving run, and the
    # cost of one validator /fleet poll over a 3-node fleet table.
    if os.environ.get("BENCH_OBS", "1") == "1" and _BERT == "base":
        try:
            out.update(observability_round())
        except Exception as e:  # noqa: BLE001 — must not sink the headline
            out["observability_error"] = str(e)[:200]

    # -- work-receipt metering cost (ISSUE 19): what per-request
    # metering + canonical-bytes receipt signing charges the serve
    # path, and the sign/audit microcosts.
    if os.environ.get("BENCH_METER", "1") == "1" and _BERT == "base":
        try:
            out.update(metering_round())
        except Exception as e:  # noqa: BLE001 — must not sink the headline
            out["metering_error"] = str(e)[:200]

    # -- int8 end-to-end quality (VERDICT #8): logit KL between bf16 and
    # int8 weight-only GPT-2 small on a fixed eval batch. The number the
    # "int8 costs ~nothing" claim rides on; tests/test_quant.py pins the
    # same quantity under a bound on a CI-sized model.
    if os.environ.get("BENCH_INT8Q", "1") == "1" and _BERT == "base":
        try:
            from tensorlink_tpu.models.gpt2 import GPT2, GPT2Config
            from tensorlink_tpu.ops.quant import quantize_params_int8

            qcfg = GPT2Config()
            qmodel = GPT2(qcfg)
            qp0 = qmodel.init(jax.random.key(0))

            def to_serving(t):
                # the engine's serving dtype policy: >=2-D float leaves
                # to bf16, 1-D (biases/norms/scales) stay f32
                return jax.tree.map(
                    lambda x: x.astype(jnp.bfloat16)
                    if jnp.issubdtype(x.dtype, jnp.floating) and x.ndim >= 2
                    else x,
                    t,
                )

            pref = to_serving(qp0)
            pq = to_serving(quantize_params_int8(qmodel, qp0))
            qids = jnp.asarray(
                np.random.default_rng(7).integers(
                    0, qcfg.vocab_size, (8, 128)
                )
            )

            @jax.jit
            def logit_kl(pa, pb, ids):
                la = qmodel.apply(pa, ids).astype(jnp.float32)
                lb = qmodel.apply(pb, ids).astype(jnp.float32)
                pa_ = jax.nn.log_softmax(la)
                pb_ = jax.nn.log_softmax(lb)
                kl = jnp.sum(jnp.exp(pa_) * (pa_ - pb_), axis=-1)
                return jnp.mean(kl), jnp.max(kl)

            kl_mean, kl_max = logit_kl(pref, pq, qids)
            out["int8_quality"] = {
                "logit_kl_mean": round(float(kl_mean), 6),
                "logit_kl_max": round(float(kl_max), 6),
                "bound": 0.02,
                "config": (
                    "GPT-2 small bf16 vs int8 weight-only, fixed batch "
                    "8x128 (KL in nats, bf16||int8)"
                ),
            }
            del pref, pq, qp0
        except Exception as e:  # noqa: BLE001
            out["int8_quality_error"] = str(e)[:200]

    # -- secondary: long-prefix serving (fresh-keys prefill + sliding
    # window + rolling ring cache, the r4 serving work). End-to-end
    # generate() = prefill + 64-step decode at prefix 3968 in an 8192
    # cache; failure-tolerant like the other secondaries.
    if os.environ.get("BENCH_SERVING", "1") == "1" and _BERT == "base":
        try:
            from tensorlink_tpu.config import MeshConfig
            from tensorlink_tpu.models.llama import Llama, LlamaConfig
            from tensorlink_tpu.parallel.inference import (
                GenerationConfig,
                InferenceEngine,
            )
            from tensorlink_tpu.runtime.mesh import make_mesh

            Bs, Ps, Ns = 4, 3968, 64
            sbase = dict(
                vocab_size=8192, dim=512, num_layers=4, num_heads=8,
                num_kv_heads=8, hidden_dim=1024, max_len=8192,
                rope_theta=10000.0,
            )
            rs = np.random.default_rng(0)
            sids = jnp.asarray(rs.integers(0, 8192, (Bs, Ps)))
            sgen = GenerationConfig(max_new_tokens=Ns)

            def serving_tps(cfg_kw, **eng_kw):
                sm = Llama(LlamaConfig(**sbase, **cfg_kw))
                sp = sm.init(jax.random.key(0))
                eng = InferenceEngine(
                    make_mesh(MeshConfig()), sm, sp, max_len=8192, **eng_kw
                )
                t = eng.generate(sids, sgen)
                int(np.asarray(t)[0, -1])  # sync (compile + first call)
                t0 = time.perf_counter()
                for _ in range(3):
                    t = eng.generate(sids, sgen)
                int(np.asarray(t)[0, -1])
                return Bs * Ns / ((time.perf_counter() - t0) / 3)

            out["serving_long_prefix_tokens_per_sec"] = round(
                serving_tps({}), 1
            )
            out["serving_windowed_tokens_per_sec"] = round(
                serving_tps({"attn_window": 512}), 1
            )
            out["serving_ring_cache_tokens_per_sec"] = round(
                serving_tps({"attn_window": 512}, rolling_cache=True), 1
            )
            out["serving_config"] = (
                f"Llama d512/L4 bf16, batch {Bs}, prefix {Ps}, {Ns} new "
                "tokens, max_len 8192; windowed/ring at window 512"
            )
        except Exception as e:  # noqa: BLE001 — must not sink the headline
            out["serving_error"] = str(e)[:200]

    if os.environ.get("BENCH_RING", "1") == "1" and _BERT == "base":
        try:
            from tensorlink_tpu.nn.attention import dot_product_attention
            from tensorlink_tpu.ops.flash import flash_attention

            Br, Tr, Hr, Dr = 2, 4096, 8, 64  # 32k tokens over a ring of 8
            ks = jax.random.split(jax.random.key(7), 3)
            qr, kr, vr = (
                jax.random.normal(k, (Br, Tr, Hr, Dr), jnp.bfloat16)
                for k in ks
            )

            def timed(f):
                g = jax.jit(jax.grad(
                    lambda q, k, v: jnp.sum(
                        f(q, k, v).astype(jnp.float32) ** 2
                    ),
                    argnums=(0, 1, 2),
                ))
                o = g(qr, kr, vr)
                float(jnp.asarray(o[0]).reshape(-1)[0].astype(jnp.float32))
                t0 = time.perf_counter()
                for _ in range(5):
                    o = g(qr, kr, vr)
                float(jnp.asarray(o[0]).reshape(-1)[0].astype(jnp.float32))
                return (time.perf_counter() - t0) / 5

            t_flash = timed(
                lambda q, k, v: flash_attention(q, k, v, causal=True)
            )
            t_einsum = timed(
                lambda q, k, v: dot_product_attention(q, k, v, causal=True)
            )
            out["ring_block_speedup"] = round(t_einsum / t_flash, 2)
            out["ring_block_config"] = (
                f"fwd+bwd, block [B{Br}, T{Tr}, H{Hr}, D{Dr}] bf16 causal "
                f"(one ring shard of a 32k-token step): flash "
                f"{t_flash*1e3:.1f} ms vs einsum {t_einsum*1e3:.1f} ms"
            )
        except Exception as e:  # noqa: BLE001
            out["ring_block_error"] = str(e)[:200]

    # -- real-size serving: Llama-3-8B int8 weight-only on the single
    # chip (BASELINE.json config[4] — previously evidenced only by a
    # shape check, VERDICT r4 next #1). Random weights in serving form
    # (quantized_random_init: the float model would be 32 GB and never
    # exists), real shapes/layout/dtypes; ~8.6 GB on the 16 GB v5e.
    if os.environ.get("BENCH_LLAMA8B", "1") == "1" and _BERT == "base":
        try:
            from tensorlink_tpu.config import MeshConfig
            from tensorlink_tpu.models.llama import Llama, LlamaConfig
            from tensorlink_tpu.ops.quant import quantized_random_init
            from tensorlink_tpu.parallel.inference import (
                GenerationConfig,
                InferenceEngine,
            )
            from tensorlink_tpu.runtime.mesh import make_mesh

            lcfg = LlamaConfig.llama3_8b()
            lmodel = Llama(lcfg)
            lqp = quantized_random_init(lmodel, jax.random.key(0))
            B8, P8, N8 = 8, 128, 64
            leng = InferenceEngine(
                make_mesh(MeshConfig()), lmodel, lqp, max_len=1024,
                quantize="int8",
            )
            lids = np.asarray(
                np.random.default_rng(0).integers(0, lcfg.vocab_size, (B8, P8))
            )
            lgen = GenerationConfig(max_new_tokens=N8)
            lt = leng.generate(lids, lgen)  # compile + first call
            assert np.isfinite(lt).all()
            reps = 3
            t0 = time.perf_counter()
            louts = [leng.generate_async(lids, lgen) for _ in range(reps)]
            int(np.asarray(louts[-1])[0, -1])
            ldt = (time.perf_counter() - t0) / reps
            ltps = B8 * N8 / ldt
            lw, lkv, lbound = decode_roofline(
                leng.params, hbm, lcfg.num_layers, B8, P8, N8,
                kv_head_dim=lcfg.num_kv_heads * (lcfg.dim // lcfg.num_heads),
                exclude="tok_emb",  # embed is a gather
            )
            out["llama8b_decode_tokens_per_sec"] = round(ltps, 1)
            if lbound:
                out["llama8b_decode_roofline"] = {
                    "weight_bytes_per_step": lw,
                    "kv_bytes_per_step": lkv,
                    "bandwidth_bound_tokens_per_sec": round(lbound, 1),
                    "fraction_attained": round(ltps / lbound, 3),
                }
            out["llama8b_config"] = (
                f"Llama-3-8B int8 weight-only (random weights, serving "
                f"form), batch {B8}, prompt {P8}, {N8} new tokens, "
                f"{reps} pipelined calls"
            )
            # speculation on the 8B: no tiny sibling in the zoo, so the
            # n-gram/prompt-lookup draft (parallel/speculative.py) —
            # the self-speculation case the fallback exists for. Same
            # verify-K program as the draft-model path.
            try:
                from tensorlink_tpu.parallel.serving import (
                    ContinuousBatchingEngine,
                    SpecConfig,
                )

                sys8 = np.random.default_rng(1).integers(
                    0, lcfg.vocab_size, (64,)
                )
                l8prompts = [
                    np.concatenate([
                        sys8,
                        np.random.default_rng(10 + i).integers(
                            0, lcfg.vocab_size, (P8 - 64,)
                        ),
                    ])
                    for i in range(4)
                ]
                l8gen = GenerationConfig(max_new_tokens=32)
                l8s = ContinuousBatchingEngine(
                    leng, slots=4, gen=l8gen, decode_chunk=8,
                    prefill_block=64, speculative=SpecConfig(k=4, rounds=1),
                )
                l8s.result(l8s.submit(l8prompts[0]))  # warm/compile
                t0 = time.perf_counter()
                l8rids = [l8s.submit(p_) for p_ in l8prompts]
                l8s.run_until_idle()
                l8dt = time.perf_counter() - t0
                l8tok = sum(len(l8s.result(r_)) for r_ in l8rids)
                l8st = l8s.stats()["spec"]
                out["llama8b_spec_tokens_per_sec"] = round(l8tok / l8dt, 1)
                out["llama8b_spec_acceptance_rate"] = l8st[
                    "acceptance_rate"
                ]
                out["llama8b_accepted_tokens_per_weight_pass"] = l8st[
                    "accepted_tokens_per_weight_pass"
                ]
                out["llama8b_spec_config"] = (
                    "n-gram self-speculation (k=4), 4 requests "
                    "(shared 64-token prefix) over 4 slots, 32 new"
                )
            except Exception as e:  # noqa: BLE001
                out["llama8b_spec_error"] = str(e)[:200]
            del leng, lqp
        except Exception as e:  # noqa: BLE001
            out["llama8b_error"] = str(e)[:200]

    # -- secondary: MoE/EP training throughput + router drop fraction
    # (VERDICT r3 weak #9: EP had zero perf evidence). Single-chip
    # measurement of a Mixtral-style MoE-GPT; failure-tolerant.
    # -- ring SP block compute: the flash kernels now run INSIDE the
    # ring (parallel/sp.py ring_flash_attention, VERDICT r4 weak #5).
    # The virtual-mesh ring can't measure real speed (1-core host), so
    # the honest single-chip number is the ring's per-rotation local
    # block math — kernel vs einsum at a representative block shape
    # (one [B, T/S, H, D] shard of a long-context training step),
    # fwd+bwd as the ring runs it.
    if os.environ.get("BENCH_MOE", "1") == "1" and _BERT == "base":
        try:
            from tensorlink_tpu.models.llama import Llama, LlamaConfig

            mcfg = LlamaConfig(
                vocab_size=8192, dim=512, num_layers=4, num_heads=8,
                num_kv_heads=8, hidden_dim=1024, max_len=512,
                moe_experts=8, moe_top_k=2,
            )
            mmodel = Llama(mcfg)
            mparams = mmodel.init(jax.random.key(0))
            mopt = make_optimizer("adam", 3e-4)
            mstate = TrainState.create(mparams, mopt)
            Bm, Tm = 8, 512
            r = np.random.default_rng(0)
            mids = jnp.asarray(r.integers(0, mcfg.vocab_size, (Bm, Tm + 1)))
            mbatch = {"input_ids": mids[:, :-1], "labels": mids[:, 1:]}

            def cast_moe(p):
                return jax.tree.map(
                    lambda a: a.astype(jnp.bfloat16)
                    if jnp.issubdtype(a.dtype, jnp.floating) else a, p,
                )

            def moe_loss(p, b):
                logits, aux = mmodel.apply_with_aux(
                    cast_moe(p), b["input_ids"]
                )
                return softmax_cross_entropy(
                    logits, b["labels"]
                ) + 0.01 * aux

            def moe_step(st, b):
                loss, grads = jax.value_and_grad(moe_loss)(st.params, b)
                upd, os_ = mopt.update(grads, st.opt_state, st.params, st.step)
                return TrainState(
                    params=apply_updates(st.params, upd),
                    opt_state=os_, step=st.step + 1,
                ), loss

            @partial(jax.jit, donate_argnums=(0,))
            def moe_multi(st, b):
                return jax.lax.scan(
                    lambda s, _: moe_step(s, b), st, None, length=10
                )

            # router drop fraction on the input layer 0's router actually
            # sees (pre-norm block order norm2(x + attn(norm1(x))) — the
            # raw embedding has a different scale/correlation and can
            # misstate capacity drops). Computed FIRST: mcomp donates
            # mstate, whose leaves alias mparams — reading them after
            # hits deleted buffers (observed live r4: "Array has been
            # deleted")
            blk = mmodel.children["blocks"].children["0"]
            emb = mmodel.children["tok_emb"].apply(
                mparams["tok_emb"], mbatch["input_ids"]
            )
            rs = blk.routing_stats(mparams["blocks"]["0"], emb)
            drop_frac = float(rs["drop_fraction"])

            mcomp = moe_multi.lower(mstate, mbatch).compile()
            mstate, ml = mcomp(mstate, mbatch)
            float(ml[-1])
            t0 = time.perf_counter()
            mstate, ml = mcomp(mstate, mbatch)
            float(ml[-1])
            dt = (time.perf_counter() - t0) / 10
            out["moe_tokens_per_sec"] = round(Bm * Tm / dt, 1)
            out["moe_router_drop_fraction"] = round(drop_frac, 4)
            out["moe_config"] = (
                f"MoE-Llama d{mcfg.dim} L{mcfg.num_layers} "
                f"E{mcfg.moe_experts} top{mcfg.moe_top_k} bf16, "
                f"batch {Bm}, seq {Tm}"
            )
        except Exception as e:  # noqa: BLE001 — must not sink the headline
            out["moe_error"] = str(e)[:200]

    # -- measured pipeline bubble (local-CPU subprocess; the bench chip
    # is a single device, so S>=2 stages cannot exist on it — see
    # _bubble_child docstring for why this is the honest venue)
    if os.environ.get("BENCH_BUBBLE", "1") == "1" and _BERT == "base":
        out["pipeline_bubble"] = measured_bubble_subprocess()

    # -- regression report vs the newest committed BENCH_r*.json: the
    # per-key deltas tldiag bench-diff computes, embedded in the record
    # (report only — a slow chip day must not fail the bench; CI policy
    # reads `regressions` if it wants to gate)
    try:
        from tensorlink_tpu.diag import bench_diff, latest_bench_record

        prev = latest_bench_record(os.path.dirname(os.path.abspath(__file__)))
        if prev is not None:
            name, rec = prev
            diff = bench_diff(rec, out, threshold=0.05)
            out["bench_diff"] = {
                "against": name,
                "regressions": {
                    k: diff["keys"][k] for k in diff["regressions"]
                },
                "improvements": diff["improvements"],
                "keys_compared": len(diff["keys"]),
            }
    except Exception as e:  # noqa: BLE001 — must not sink the headline
        out["bench_diff_error"] = str(e)[:200]

    base = read_recorded_baseline()
    out["vs_baseline"] = round(samples_per_sec_per_chip / base, 3) if base else 1.0
    # the round-1 denominator was measured with per-call dispatch overhead
    # (10 steps/call); r3+ amortize dispatch (50 steps/call), so part of
    # vs_baseline is methodology, not compute. MFU is the cross-round
    # anchor (VERDICT r3 weak #2).
    out["vs_baseline_note"] = (
        "denominator recorded r1 at 10 steps/call (dispatch-bound); "
        "mfu is the comparable cross-round anchor"
    )
    print(json.dumps(out))


if __name__ == "__main__":
    if "--bubble-child" in sys.argv:
        _bubble_child()
    else:
        main()
