"""Headline benchmark: BERT-base fine-tune throughput (samples/sec/chip).

The reference's implied e2e workload is a BERT-base sequence-classification
fine-tune (tests/ml/test_full_train.py:56-179 — batch 1, seq 100, Adam) for
which it publishes no numbers (BASELINE.md). We run the same workload shape
TPU-natively: bf16 compute, jit train step, K steps chained inside one
device program (lax.scan) so host/tunnel dispatch overhead is amortized.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
vs_baseline compares against the round-1 recorded value in BASELINE.md
(1.0 when no prior recording exists).
"""

from __future__ import annotations

import json
import re
import sys
import time
from pathlib import Path

import jax
import jax.numpy as jnp
import numpy as np

from tensorlink_tpu.config import TrainConfig
from tensorlink_tpu.models.bert import BertClassifier, BertConfig
from tensorlink_tpu.train.optim import apply_updates, make_optimizer
from tensorlink_tpu.train.trainer import TrainState, softmax_cross_entropy

import os

BATCH = int(os.environ.get("BENCH_BATCH", 32))
SEQ = int(os.environ.get("BENCH_SEQ", 128))
CLASSES = 3
STEPS_PER_CALL = int(os.environ.get("BENCH_STEPS_PER_CALL", 10))
MEASURE_CALLS = int(os.environ.get("BENCH_MEASURE_CALLS", 3))
_BERT = os.environ.get("BENCH_BERT", "base")  # "base" | "tiny" (smoke only)

# Peak bf16 matmul TFLOP/s per chip by device kind (public spec sheets);
# substring-matched against jax device_kind. Used only to report MFU.
PEAK_BF16_TFLOPS = (
    ("v5p", 459.0),
    ("v5e", 197.0),
    ("v5 lite", 197.0),
    ("v6e", 918.0),
    ("v6 lite", 918.0),
    ("v4", 275.0),
    ("v3", 123.0),
    ("v2", 45.0),
)


def peak_tflops_for(device_kind: str) -> float | None:
    dk = device_kind.lower()
    for key, tf in PEAK_BF16_TFLOPS:
        if key in dk:
            return tf
    return None


def backend_with_retry(attempts: int = 4, delay_s: float = 10.0):
    """Initialize the accelerator backend, retrying transient tunnel
    failures ('Unable to initialize backend'); returns jax.devices().

    The round-1 bench died rc=1 on a single flaky backend init
    (BENCH_r01.json). Bounded retry, then a clear JSON error.
    """
    last = None
    for i in range(attempts):
        try:
            return jax.devices()
        except RuntimeError as e:  # jax raises RuntimeError on backend init
            last = e
            if "nable to initialize backend" not in str(e):
                raise
            try:
                import jax.extend.backend as _jeb

                _jeb.clear_backends()
            except Exception:
                pass
            time.sleep(delay_s * (i + 1))
    print(
        json.dumps(
            {
                "metric": f"samples/sec/chip (BERT-{_BERT} fine-tune, batch {BATCH}, seq {SEQ}, bf16)",
                "value": 0.0,
                "unit": "samples/sec/chip",
                "vs_baseline": 0.0,
                "error": f"backend init failed after {attempts} attempts: {last}",
            }
        )
    )
    sys.exit(1)


def build():
    cfg = BertConfig.tiny() if _BERT == "tiny" else BertConfig.base()
    model = BertClassifier(cfg, num_classes=CLASSES)
    params = model.init(jax.random.key(0))
    opt = make_optimizer("adam", 2e-5)
    state = TrainState.create(params, opt)

    r = np.random.default_rng(0)
    batch = {
        "input_ids": jnp.asarray(r.integers(0, cfg.vocab_size, (BATCH, SEQ))),
        "attention_mask": jnp.ones((BATCH, SEQ), jnp.int32),
        "labels": jnp.asarray(r.integers(0, CLASSES, (BATCH,))),
    }

    def cast(p):
        return jax.tree.map(
            lambda x: x.astype(jnp.bfloat16)
            if jnp.issubdtype(x.dtype, jnp.floating)
            else x,
            p,
        )

    def loss_fn(params, batch):
        logits = model.apply(
            cast(params), batch["input_ids"], attention_mask=batch["attention_mask"]
        )
        return softmax_cross_entropy(logits, batch["labels"])

    def one_step(state: TrainState, batch):
        loss, grads = jax.value_and_grad(loss_fn)(state.params, batch)
        updates, opt_state = opt.update(grads, state.opt_state, state.params, state.step)
        return (
            TrainState(
                params=apply_updates(state.params, updates),
                opt_state=opt_state,
                step=state.step + 1,
            ),
            loss,
        )

    @jax.jit
    def multi_step(state, batch):
        def body(s, _):
            s, loss = one_step(s, batch)
            return s, loss

        state, losses = jax.lax.scan(body, state, None, length=STEPS_PER_CALL)
        return state, losses

    return state, batch, multi_step


def read_recorded_baseline() -> float | None:
    """First recorded samples/sec/chip in BASELINE.md, if any."""
    p = Path(__file__).parent / "BASELINE.md"
    if not p.exists():
        return None
    m = re.search(r"recorded_samples_per_sec_per_chip:\s*([0-9.]+)", p.read_text())
    return float(m.group(1)) if m else None


def count_step_flops(params) -> float:
    """Analytic FLOPs for one train step: ~6 * params * tokens
    (2PT forward + 4PT backward) — the standard transformer estimate."""
    n_params = sum(int(np.prod(x.shape)) for x in jax.tree.leaves(params))
    return 6.0 * n_params * BATCH * SEQ


def main() -> None:
    devices = backend_with_retry()
    device_kind = devices[0].device_kind

    state, batch, multi_step = build()
    # AOT-compile ONCE and reuse the executable for warmup, measurement,
    # and cost_analysis — calling the jit wrapper AND lower().compile()
    # would compile the 10-step scanned program twice (review finding)
    compiled = multi_step.lower(state, batch).compile()
    # warmup; the trailing float() is a device->host read that REALLY
    # synchronizes (block_until_ready alone does not drain the async
    # dispatch queue on tunneled TPU runtimes)
    state, losses = compiled(state, batch)
    float(losses[-1])

    t0 = time.perf_counter()
    for _ in range(MEASURE_CALLS):
        state, losses = compiled(state, batch)
    float(losses[-1])
    dt = time.perf_counter() - t0

    n_steps = MEASURE_CALLS * STEPS_PER_CALL
    # the un-sharded jit step runs on exactly one chip regardless of how
    # many the host exposes
    chips = 1
    samples_per_sec_per_chip = BATCH * n_steps / dt / chips

    # MFU: prefer XLA's own cost analysis of the compiled program (exact
    # for the program as run), fall back to the 6PT analytic estimate.
    try:
        cost = compiled.cost_analysis()
        if isinstance(cost, (list, tuple)):
            cost = cost[0]
        flops_per_step = float(cost["flops"]) / STEPS_PER_CALL
        flops_src = "xla_cost_analysis"
    except Exception:
        flops_per_step = count_step_flops(state.params)
        flops_src = "analytic_6PT"
    steps_per_sec = n_steps / dt
    achieved_tflops = flops_per_step * steps_per_sec / 1e12
    peak = peak_tflops_for(device_kind)
    mfu = achieved_tflops / peak if peak else None

    base = read_recorded_baseline()
    vs = samples_per_sec_per_chip / base if base else 1.0
    print(
        json.dumps(
            {
                "metric": f"samples/sec/chip (BERT-{_BERT} fine-tune, batch {BATCH}, seq {SEQ}, bf16)",
                "value": round(samples_per_sec_per_chip, 2),
                "unit": "samples/sec/chip",
                "vs_baseline": round(vs, 3),
                "device_kind": device_kind,
                "achieved_tflops": round(achieved_tflops, 2),
                "peak_bf16_tflops": peak,
                "mfu": round(mfu, 4) if mfu is not None else None,
                "flops_source": flops_src,
            }
        )
    )


if __name__ == "__main__":
    main()
