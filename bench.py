"""Headline benchmark: BERT-base fine-tune throughput (samples/sec/chip).

The reference's implied e2e workload is a BERT-base sequence-classification
fine-tune (tests/ml/test_full_train.py:56-179 — batch 1, seq 100, Adam) for
which it publishes no numbers (BASELINE.md). We run the same workload shape
TPU-natively: bf16 compute, jit train step, K steps chained inside one
device program (lax.scan) so host/tunnel dispatch overhead is amortized.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
vs_baseline compares against the round-1 recorded value in BASELINE.md
(1.0 when no prior recording exists).
"""

from __future__ import annotations

import json
import re
import sys
import time
from pathlib import Path

import jax
import jax.numpy as jnp
import numpy as np

from tensorlink_tpu.config import TrainConfig
from tensorlink_tpu.models.bert import BertClassifier, BertConfig
from tensorlink_tpu.train.optim import apply_updates, make_optimizer
from tensorlink_tpu.train.trainer import TrainState, softmax_cross_entropy

BATCH = 32
SEQ = 128
CLASSES = 3
STEPS_PER_CALL = 10
MEASURE_CALLS = 3


def build():
    cfg = BertConfig.base()
    model = BertClassifier(cfg, num_classes=CLASSES)
    params = model.init(jax.random.key(0))
    opt = make_optimizer("adam", 2e-5)
    state = TrainState.create(params, opt)

    r = np.random.default_rng(0)
    batch = {
        "input_ids": jnp.asarray(r.integers(0, cfg.vocab_size, (BATCH, SEQ))),
        "attention_mask": jnp.ones((BATCH, SEQ), jnp.int32),
        "labels": jnp.asarray(r.integers(0, CLASSES, (BATCH,))),
    }

    def cast(p):
        return jax.tree.map(
            lambda x: x.astype(jnp.bfloat16)
            if jnp.issubdtype(x.dtype, jnp.floating)
            else x,
            p,
        )

    def loss_fn(params, batch):
        logits = model.apply(
            cast(params), batch["input_ids"], attention_mask=batch["attention_mask"]
        )
        return softmax_cross_entropy(logits, batch["labels"])

    def one_step(state: TrainState, batch):
        loss, grads = jax.value_and_grad(loss_fn)(state.params, batch)
        updates, opt_state = opt.update(grads, state.opt_state, state.params, state.step)
        return (
            TrainState(
                params=apply_updates(state.params, updates),
                opt_state=opt_state,
                step=state.step + 1,
            ),
            loss,
        )

    @jax.jit
    def multi_step(state, batch):
        def body(s, _):
            s, loss = one_step(s, batch)
            return s, loss

        state, losses = jax.lax.scan(body, state, None, length=STEPS_PER_CALL)
        return state, losses

    return state, batch, multi_step


def read_recorded_baseline() -> float | None:
    """First recorded samples/sec/chip in BASELINE.md, if any."""
    p = Path(__file__).parent / "BASELINE.md"
    if not p.exists():
        return None
    m = re.search(r"recorded_samples_per_sec_per_chip:\s*([0-9.]+)", p.read_text())
    return float(m.group(1)) if m else None


def main() -> None:
    state, batch, multi_step = build()
    # compile + warmup; the trailing float() is a device->host read that
    # REALLY synchronizes (block_until_ready alone does not drain the
    # async dispatch queue on tunneled TPU runtimes)
    state, losses = multi_step(state, batch)
    float(losses[-1])

    t0 = time.perf_counter()
    for _ in range(MEASURE_CALLS):
        state, losses = multi_step(state, batch)
    float(losses[-1])
    dt = time.perf_counter() - t0

    n_steps = MEASURE_CALLS * STEPS_PER_CALL
    # the un-sharded jit step runs on exactly one chip regardless of how
    # many the host exposes
    chips = 1
    samples_per_sec_per_chip = BATCH * n_steps / dt / chips
    base = read_recorded_baseline()
    vs = samples_per_sec_per_chip / base if base else 1.0
    print(
        json.dumps(
            {
                "metric": "samples/sec/chip (BERT-base fine-tune, batch 32, seq 128, bf16)",
                "value": round(samples_per_sec_per_chip, 2),
                "unit": "samples/sec/chip",
                "vs_baseline": round(vs, 3),
            }
        )
    )


if __name__ == "__main__":
    main()
